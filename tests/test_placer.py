"""Autonomous placement + elastic fleet tests (xgboost_tpu.placer;
SERVING.md "Autonomous placement").

Acceptance criteria covered here (ISSUE 16):
(a) end-to-end autonomy: an empty 2-replica fleet plus a placer with a
    4-tenant catalog ends with every tenant placed within device
    budgets and serving through the router;
(b) minimal remap: a load shift rebalances ONLY the shifted tenant(s),
    and the HashRing property holds — a delta moving K of M models
    remaps only keys owned by those K, including under concurrent
    replica death;
(c) failure re-homing: killing one replica re-homes its models onto
    the survivors with zero non-shed request failures afterward;
(d) durability: the target plan round-trips through the CRC-footered
    snapshot, a truncated snapshot replans cold instead of raising,
    and a resumed placer starts from its predecessor's plan;
(e) single-holder lease: a standby placer's ticks are no-ops until the
    holder's lease expires; plan recording from a non-holder is 409;
(f) manifest deltas: the replica ``POST /-/catalog`` surface attaches
    tolerantly, detaches idempotently, and refuses the pinned default;
(g) heartbeat advertisement drift (bugfix): a catalog delta pushed
    straight to a replica reaches the router's hosting map within ONE
    heartbeat, device budgets ride along, and the diff is counted;
(h) elastic band: the supervisor scales up/down one replica per
    cooldown, holds resizes while a rollout soak is in flight, and
    freezes the fleet when the router is unreachable.
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.fleet import FleetRouter, scrape_labeled_samples
from xgboost_tpu.fleet.membership import HashRing
from xgboost_tpu.fleet.rollout import scrape_samples
from xgboost_tpu.placer import (ElasticSupervisor, PlacementController,
                                run_placer)
from xgboost_tpu.serving import run_server


@pytest.fixture(scope="module")
def model_file(tmp_path_factory):
    rng = np.random.RandomState(0)
    X = rng.rand(200, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 0.4, "silent": 1},
                    xgb.DMatrix(X, label=y), 3)
    path = str(tmp_path_factory.mktemp("placer") / "model.bin")
    bst.save_model(path)
    return path, X


def _post(url, payload=None, data=None):
    body = (json.dumps(payload).encode() if payload is not None
            else (data or b""))
    req = urllib.request.Request(url, data=body, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            return e.code, json.loads(raw)
        except ValueError:
            return e.code, {}


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def _csv(rows):
    return "\n".join(",".join(f"{v:.6f}" for v in row)
                     for row in rows).encode()


def _replica(catalog, router_url="", rid="", **kw):
    return run_server("", catalog=catalog, port=0, min_bucket=8,
                      max_bucket=32, max_wait_ms=1.0, poll_sec=0,
                      warmup=False, quiet=True, block=False,
                      router_url=router_url, replica_id=rid,
                      catalog_mb=64.0, **kw)


def _members(rids, models=None, budget=0):
    """A fake /fleet/members payload for pure plan() tests."""
    return {"replicas": [
        {"replica_id": r, "url": f"http://127.0.0.1:1/{r}",
         "in_rotation": True,
         "models": sorted((models or {}).get(r, [])),
         "models_detail": {m: {} for m in (models or {}).get(r, [])},
         "device": {"budget_bytes": budget}}
        for r in rids]}


# ------------------------------------------------- ring minimal remap
@pytest.mark.parametrize("n_replicas,killed", [(4, 1), (6, 2), (3, 1)])
def test_hashring_minimal_remap(n_replicas, killed):
    """(b) a replica death remaps ONLY the keys that replica owned —
    first through eligibility filtering (the concurrent-death view,
    before any rebuild) and then through a rebuild on the survivors,
    and the two agree."""
    ring = HashRing(64)
    rids = [f"r{i}" for i in range(n_replicas)]
    ring.rebuild(rids)
    keys = [f"m{i}#{s}" for i in range(25) for s in range(2)]
    before = {k: ring.route(k, set(rids)) for k in keys}
    assert len(set(before.values())) > 1, "degenerate ring"
    dead = set(rids[:killed])
    live = set(rids) - dead
    # concurrent death: the ring still holds the dead vnodes, dispatch
    # filters by eligibility — survivors' keys must not move
    during = {k: ring.route(k, live) for k in keys}
    moved = [k for k in keys if during[k] != before[k]]
    assert all(before[k] in dead for k in moved)
    assert all(during[k] in live for k in keys)
    # rebuild on the survivors gives the SAME answer: failover is not
    # a transient that a later rebuild reshuffles
    ring.rebuild(sorted(live))
    after = {k: ring.route(k, live) for k in keys}
    assert after == during


def test_plan_minimal_remap_on_load_shift():
    """(b) at the plan level: a load shift on one tenant adds hosts
    for THAT tenant and leaves every other assignment untouched."""
    manifest = {f"t{i}": f"/nonexistent/t{i}.bin" for i in range(8)}
    ctl = PlacementController("http://127.0.0.1:9", manifest,
                              replication=1, hot_replication=2,
                              hot_fraction=0.5)
    rids = ["r0", "r1", "r2", "r3"]
    base = ctl.plan(_members(rids))
    assert all(len(v) == 1 and v[0] in rids for v in base.values())
    ctl.target = base
    # t3 goes hot: >= half the fleet's load -> replication floor 2
    ctl.loads = {t: 0.0 for t in manifest}
    ctl.loads["t3"] = 100.0
    shifted = ctl.plan(_members(rids))
    assert len(shifted["t3"]) == 2 and set(base["t3"]) <= set(shifted["t3"])
    for t in manifest:
        if t != "t3":
            assert shifted[t] == base[t], f"{t} moved on t3's load shift"
    # a replica death moves only ITS tenants (stickiness + ring anchor)
    ctl.target = shifted
    survivors = [r for r in rids if r != "r0"]
    rehomed = ctl.plan(_members(survivors))
    for t in manifest:
        kept = [r for r in shifted[t] if r != "r0"]
        assert set(kept) <= set(rehomed[t])
        if "r0" not in shifted[t]:
            assert rehomed[t] == shifted[t], f"{t} moved on r0's death"
        else:
            assert "r0" not in rehomed[t]


def test_plan_respects_device_budget_and_spills(tmp_path):
    """A tenant is packed onto replicas with headroom; when nothing
    fits it is STILL placed (least-used) — over budget beats orphaned."""
    p = tmp_path / "m.bin"
    p.write_bytes(b"x" * 600)
    manifest = {"a": str(p), "b": str(p), "c": str(p)}
    ctl = PlacementController("http://127.0.0.1:9", manifest)
    target = ctl.plan(_members(["r0", "r1"], budget=1000))
    # each replica fits exactly one 600-byte model under a 1000-byte
    # budget; the third spills instead of orphaning
    placed = [r for hosts in target.values() for r in hosts]
    assert sorted(target) == ["a", "b", "c"]
    assert all(len(v) == 1 for v in target.values())
    assert set(placed) == {"r0", "r1"}


# ---------------------------------------------------- plan durability
def test_plan_snapshot_resume_and_corrupt(tmp_path):
    """(d) CRC-footered snapshot round-trip; truncation replans cold;
    tenants no longer in the manifest are filtered on restore."""
    plan = str(tmp_path / "plan.bin")
    manifest = {"a": "/m/a.bin", "b": "/m/b.bin"}
    ctl = PlacementController("http://127.0.0.1:9", manifest,
                              plan_path=plan)
    ctl.target = {"a": ["r1"], "b": ["r1", "r2"]}
    ctl.plan_seq = 7
    ctl._snapshot_plan()
    assert os.path.exists(plan)
    ctl2 = PlacementController("http://127.0.0.1:9", manifest,
                               plan_path=plan)
    assert ctl2.target == ctl.target and ctl2.plan_seq == 7
    # a tenant dropped from the manifest does not resurrect
    ctl3 = PlacementController("http://127.0.0.1:9", {"a": "/m/a.bin"},
                               plan_path=plan)
    assert ctl3.target == {"a": ["r1"]}
    # truncated snapshot: cold start, no exception
    with open(plan, "r+b") as f:
        f.truncate(10)
    ctl4 = PlacementController("http://127.0.0.1:9", manifest,
                               plan_path=plan)
    assert ctl4.target == {} and ctl4.plan_seq == 0


# -------------------------------------------------------------- lease
def test_placer_lease_single_holder_and_plan_record():
    """(e) one placer drives at a time: the standby's tick is a no-op,
    the holder's death hands over within the lease, and only the
    holder may record a plan (409 otherwise)."""
    rt = FleetRouter(port=0, hc_sec=0, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    try:
        c1 = PlacementController(base, {}, placer_id="p1", lease_sec=1.0)
        c2 = PlacementController(base, {}, placer_id="p2", lease_sec=1.0)
        assert c1._acquire_lease() is True
        assert c2._acquire_lease() is False
        assert c1._acquire_lease() is True          # renewal
        assert c2.tick() == {"standby": True}
        st = _get(base + "/placer/status")
        assert st["holder"] == "p1" and st["lease_remaining_sec"] > 0
        # only the holder records plans
        code, _ = _post(base + "/placer/plan",
                        {"placer_id": "p1",
                         "plan": {"seq": 3, "target": {"a": ["r1"]}}})
        assert code == 200
        code, err = _post(base + "/placer/plan",
                          {"placer_id": "p2", "plan": {"seq": 9}})
        assert code == 409 and err["holder"] == "p1"
        assert _get(base + "/placer/status")["plan"]["seq"] == 3
        # holder stops renewing: the standby takes over after expiry
        time.sleep(1.1)
        assert c2._acquire_lease() is True
        assert _get(base + "/placer/status")["holder"] == "p2"
    finally:
        rt.shutdown()


# ---------------------------------------------------- manifest deltas
def test_catalog_delta_endpoint(model_file, tmp_path):
    """(f) POST /-/catalog: tolerant attach, idempotent detach, the
    pinned default refuses, missing files error without wedging the
    rest of the delta."""
    path, X = model_file
    srv = _replica(f"d={path}")
    base = f"http://127.0.0.1:{srv.port}"
    try:
        st, r = _post(base + "/-/catalog", {"add": {"b": path}})
        assert st == 200 and r["added"] == ["b"]
        assert sorted(r["models"]) == ["b", "d"]
        Q = np.round(X[:3], 6)
        st, pr = _post(base + "/predict?model=b", data=_csv(Q))
        assert st == 200 and pr["model"] == "b" and pr["rows"] == 3
        # retrying the same attach is convergence, not an error
        st, r = _post(base + "/-/catalog", {"add": {"b": path}})
        assert st == 200 and r["skipped"] == ["b"] and not r["added"]
        # missing file errors; the valid part of the delta still lands
        st, r = _post(base + "/-/catalog",
                      {"add": {"c": str(tmp_path / "nope.bin")},
                       "remove": ["b"]})
        assert st == 409 and r["removed"] == ["b"] and r["errors"]
        st, _ = _post(base + "/predict?model=b", data=_csv(Q))
        assert st == 404
        # detach of an unknown name is idempotent
        st, r = _post(base + "/-/catalog", {"remove": ["b"]})
        assert st == 200 and not r["removed"] and not r["errors"]
        # the pinned default never detaches
        st, r = _post(base + "/-/catalog", {"remove": ["d"]})
        assert st == 409 and "default" in r["errors"][0]
        st, _ = _post(base + "/predict", data=_csv(Q))
        assert st == 200
    finally:
        srv.shutdown()


# ------------------------------------------------ heartbeat drift fix
def test_heartbeat_carries_catalog_and_device_drift(model_file):
    """(g) a delta pushed straight to the replica reaches the router's
    hosting map within one heartbeat — the heartbeat payload carries
    the full model map + device budget and the router diffs it."""
    path, _ = model_file
    rt = FleetRouter(port=0, hc_sec=0, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    srv = _replica(f"a={path}", router_url=base, rid="r1")
    try:
        rep = rt.membership.get("r1")
        assert rt.membership.hosting("a") == {"r1"}
        assert rep.device["budget_bytes"] == 64 * 10**6
        before = scrape_samples(
            urllib.request.urlopen(base + "/metrics", timeout=30)
            .read().decode()).get("xgbtpu_fleet_advert_updates_total", 0)
        st, _ = _post(f"http://127.0.0.1:{srv.port}/-/catalog",
                      {"add": {"b": path}})
        assert st == 200
        assert rt.membership.hosting("b") == set()   # not yet heartbeat
        srv.lease_client._heartbeat_once()
        assert rt.membership.hosting("b") == {"r1"}
        assert "b" in rt.membership.get("r1").models
        after = scrape_samples(
            urllib.request.urlopen(base + "/metrics", timeout=30)
            .read().decode())["xgbtpu_fleet_advert_updates_total"]
        assert after >= before + 1
        # an unchanged advertisement is NOT a diff
        srv.lease_client._heartbeat_once()
        final = scrape_samples(
            urllib.request.urlopen(base + "/metrics", timeout=30)
            .read().decode())["xgbtpu_fleet_advert_updates_total"]
        assert final == after
    finally:
        srv.shutdown()
        rt.shutdown()


def test_scrape_labeled_samples():
    text = ("# HELP xgbtpu_tenant_requests_total per-tenant\n"
            'xgbtpu_tenant_requests_total{model="a"} 42\n'
            'xgbtpu_tenant_requests_total{model="b"} 7.5\n'
            'xgbtpu_tenant_shed_total{model="a"} 3\n'
            "xgbtpu_fleet_dispatch_total 9\n")
    assert scrape_labeled_samples(
        text, "xgbtpu_tenant_requests_total") == {"a": 42.0, "b": 7.5}
    assert scrape_labeled_samples(text, "xgbtpu_missing") == {}
    # the unlabeled parser still skips labeled samples (gate contract)
    assert "xgbtpu_tenant_requests_total" not in scrape_samples(text)


# ------------------------------------------------------- elastic band
def test_elastic_supervisor_band():
    """(h) band state machine: scale up above the band, down below it,
    one resize per cooldown, hold while a rollout soak is in flight,
    freeze when the router probe fails."""
    state = {"n": 2, "inflight": 4, "rollout": False}

    def probe():
        return {"members": state["n"], "inflight": state["inflight"],
                "rollout_in_progress": state["rollout"]}

    def spawn():
        state["n"] += 1

    def drain():
        state["n"] -= 1
        return f"r{state['n']}"

    sup = ElasticSupervisor("http://127.0.0.1:9", spawn, drain,
                            lambda: state["n"], min_replicas=1,
                            max_replicas=4, util_low=0.2, util_high=0.6,
                            util_alpha=1.0, replica_slots=4,
                            cooldown_sec=60.0, probe_fn=probe)
    # util = 4 / (4 slots * 2 replicas) = 0.5: inside the band
    assert sup.tick()["state"] == "steady" and state["n"] == 2
    # above the band: one spawn, then the cooldown gates the next
    state["inflight"] = 16
    assert sup.tick()["state"] == "scale_up" and state["n"] == 3
    assert sup.tick()["state"] == "steady" and state["n"] == 3
    sup._last_resize -= 61.0
    # below the band during a rollout soak: HOLD, fleet size pinned
    state["inflight"] = 0
    state["rollout"] = True
    holds0 = sup.metrics.resize_holds.value
    assert sup.tick()["state"] == "hold" and state["n"] == 3
    assert sup.metrics.resize_holds.value == holds0 + 1
    # soak settles: the withheld drain goes through
    state["rollout"] = False
    assert sup.tick()["state"] == "scale_down" and state["n"] == 2
    sup._last_resize -= 61.0
    assert sup.tick()["state"] == "scale_down" and state["n"] == 1
    # the floor: never below min_replicas
    sup._last_resize -= 61.0
    assert sup.tick()["state"] == "steady" and state["n"] == 1
    # router unreachable: freeze, report the error, change nothing
    def bad_probe():
        raise OSError("router down")
    sup.probe_fn = bad_probe
    r = sup.tick()
    assert r["state"] == "steady" and "error" in r and state["n"] == 1


# ------------------------------------------------------- end to end
def test_placer_end_to_end_autonomy(model_file, tmp_path):
    """(a)+(b)+(c)+(d): empty 2-replica fleet + placer + 4-tenant
    manifest -> everything placed and serving; a load shift rebalances
    only the shifted tenant; a replica death re-homes its models with
    every post-convergence request succeeding; a second placer resumes
    the CRC-snapshotted plan."""
    path, X = model_file
    plan_path = str(tmp_path / "placer.plan")
    rt = FleetRouter(port=0, hc_sec=0, lease_sec=30.0, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    s1 = _replica(f"d1={path}", router_url=base, rid="r1")
    s2 = _replica(f"d2={path}", router_url=base, rid="r2")
    manifest = {f"t{i}": path for i in range(1, 5)}
    ctl = run_placer(base, manifest, block=False, plan_path=plan_path,
                     placer_id="e2e", lease_sec=30.0, replication=1,
                     hot_replication=2, hot_fraction=0.5, load_alpha=1.0)

    def heartbeat(*servers):
        for s in servers:
            s.lease_client._heartbeat_once()

    def settle(servers, ticks=4):
        report = {}
        for _ in range(ticks):
            report = ctl.tick()
            heartbeat(*servers)
            if report.get("converged"):
                break
        return report

    try:
        report = settle([s1, s2])
        assert report["converged"], report
        hosted = rt.membership.models_hosted()
        for t in manifest:
            assert hosted.get(t, 0) >= 1, f"{t} orphaned: {hosted}"
        # within budget on both replicas
        for rid in ("r1", "r2"):
            dev = rt.membership.get(rid).device
            assert dev["used_bytes"] <= dev["budget_bytes"]
        # every tenant actually serves through the router
        Q = np.round(X[:3], 6)
        for t in manifest:
            st, pr = _post(base + f"/predict?model={t}", data=_csv(Q))
            assert st == 200 and pr["rows"] == 3, (t, st, pr)
        target0 = {t: list(v) for t, v in ctl.target.items()}
        assert all(len(v) == 1 for v in target0.values())

        # ---- skewed load: t1 takes the whole request stream
        for _ in range(25):
            _post(base + "/predict?model=t1", data=_csv(Q[:1]))
        ctl.tick()                      # observes the shift, replans
        heartbeat(s1, s2)
        target1 = {t: list(v) for t, v in ctl.target.items()}
        assert len(target1["t1"]) == 2, target1   # hot floor kicked in
        assert set(target0["t1"]) <= set(target1["t1"])
        for t in ("t2", "t3", "t4"):
            assert target1[t] == target0[t], "unshifted tenant moved"

        # ---- a resumed placer starts from the snapshotted plan
        ctl2 = run_placer(base, manifest, block=False,
                          plan_path=plan_path, placer_id="resumed")
        assert ctl2.target == ctl.target
        assert ctl2.tick() == {"standby": True}   # e2e holds the lease

        # ---- replica death: its models re-home to the survivor
        victims = {t for t, hosts in target1.items() if "r2" in hosts}
        s2.shutdown()                   # drain deregisters immediately
        report = settle([s1])
        assert report["converged"], report
        for t in manifest:
            assert rt.membership.hosting(t) == {"r1"}, t
        assert victims, "degenerate split: r2 hosted nothing"
        # zero non-shed failures once converged
        for t in manifest:
            st, pr = _post(base + f"/predict?model={t}", data=_csv(Q))
            assert st == 200, (t, st, pr)
    finally:
        s1.shutdown()
        try:
            s2.shutdown()
        except Exception:
            pass
        rt.shutdown()

"""Multi-tenant catalog tests (xgboost_tpu.catalog; SERVING.md catalog
section).

Acceptance criteria covered here (ISSUE 14):
(a) per-model bitwise parity: every catalog entry predicts EXACTLY
    like a standalone engine built from the same file — including two
    width-divergent models resident on one replica at once;
(b) budget: admitting past ``serve_catalog_mb`` LRU-evicts the coldest
    non-default entry, a later request re-admits it, the default entry
    is pinned, and a hot model's executables survive the churn (zero
    recompiles, recompile_guard-pinned);
(c) HTTP surface: ``?model=`` on /predict, bare /predict == the
    default model, per-model /healthz rows with content hashes,
    unknown models 404 with the known list;
(d) model-aware routing: the router learns hosting from
    registration/heartbeat advertisements and sends ``?model=`` only
    to hosting replicas;
(e) tenant isolation: one tenant blowing its token-bucket rate sheds
    429 while a neighbor's requests all succeed;
(f) router zero-downtime restart: the membership snapshot round-trips
    through the CRC-footered state file;
(g) per-tenant rollout/rollback: pushing tenant A's lane moves A's
    served hash and leaves B's pinned;
(h) per-tenant training lanes: one lane erroring never stalls its
    neighbor, and each lane keeps its own gated-hash ledger.
"""

import hashlib
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.catalog import (ModelCatalog, TenantQuotas, UnknownModel,
                                 parse_manifest)
from xgboost_tpu.fleet import FleetRouter
from xgboost_tpu.fleet.membership import Membership
from xgboost_tpu.serving import ModelRegistry, PredictEngine, run_server


def _train(seed=0, rounds=3, n_features=6, **params):
    rng = np.random.RandomState(seed)
    X = rng.rand(300, n_features).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    p = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.4,
         "silent": 1, "seed": seed, **params}
    return xgb.train(p, xgb.DMatrix(X, label=y), rounds), X


@pytest.fixture(scope="module")
def models(tmp_path_factory):
    """Two width-divergent tenants: 6-feature model a, 4-feature
    model b (different seeds, depths, and row widths)."""
    d = tmp_path_factory.mktemp("catalog")
    bst_a, Xa = _train(seed=0, rounds=3, n_features=6)
    bst_b, Xb = _train(seed=7, rounds=4, n_features=4, max_depth=2)
    pa, pb = str(d / "model_a.bin"), str(d / "model_b.bin")
    bst_a.save_model(pa)
    bst_b.save_model(pb)
    return bst_a, bst_b, Xa, Xb, pa, pb


def _file_hash(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _registry_factory(**kw):
    def make(path):
        reg = ModelRegistry(path, warmup=True, poll_sec=0,
                            min_bucket=8, max_bucket=16, **kw)
        return reg
    return make


def _post(url, payload=None, data=None, headers=None):
    body = (json.dumps(payload).encode() if payload is not None
            else (data or b""))
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            raw = r.read()
            return r.status, json.loads(raw)
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            return e.code, json.loads(raw)
        except ValueError:
            return e.code, {}


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def _csv(rows):
    return "\n".join(",".join(f"{v:.6f}" for v in row)
                     for row in rows).encode()


def _catalog_replica(catalog, router_url="", rid="", port=0, **kw):
    return run_server("", catalog=catalog, port=port, min_bucket=8,
                      max_bucket=32, max_wait_ms=1.0, poll_sec=0,
                      warmup=False, quiet=True, block=False,
                      router_url=router_url, replica_id=rid, **kw)


# ------------------------------------------------------------- manifest
def test_parse_manifest_inline_and_file(tmp_path):
    assert parse_manifest("a=./a.bin, b=./b.bin") == {
        "a": "./a.bin", "b": "./b.bin"}
    mf = tmp_path / "catalog.conf"
    mf.write_text("# tenants\na = ./a.bin\nb = ./b.bin\n")
    assert parse_manifest(str(mf)) == {"a": "./a.bin", "b": "./b.bin"}
    with pytest.raises(ValueError):
        parse_manifest("a=,b=./b.bin")


# ----------------------------------------------------- quotas (units)
def test_tenant_quotas_inflight_and_rate():
    q = TenantQuotas(inflight_limit=1, rate=0.0)
    assert q.try_admit("a") is None
    assert q.try_admit("a") == "inflight"   # 503: budget is per tenant
    assert q.try_admit("b") is None         # neighbor unaffected
    q.release("a")
    assert q.try_admit("a") is None
    q.release("a")
    q.release("b")
    # token bucket: burst tokens up front, then the sustained rate
    q = TenantQuotas(rate=1.0, burst=2.0)
    assert q.try_admit("a") is None and q.try_admit("a") is None
    assert q.try_admit("a") == "rate"       # bucket drained -> 429
    assert q.try_admit("b") is None         # b's bucket is its own
    assert not TenantQuotas().enabled and TenantQuotas(rate=1.0).enabled


# ------------------------------------------------------------- parity
def test_catalog_bitwise_parity_width_divergent(models):
    """(a) two width-divergent tenants resident at once, each bitwise
    equal to a standalone engine on the same file."""
    bst_a, bst_b, Xa, Xb, pa, pb = models
    cat = ModelCatalog(registry_factory=_registry_factory())
    cat.add_model("a", pa)
    cat.add_model("b", pb)
    ea = cat.resolve("a").registry.engine
    eb = cat.resolve("b").registry.engine
    assert ea.num_feature == 6 and eb.num_feature == 4
    ref_a = PredictEngine(pa, min_bucket=8, max_bucket=16)
    ref_b = PredictEngine(pb, min_bucket=8, max_bucket=16)
    for n in (1, 7, 16, 33):
        Qa = np.random.RandomState(n).rand(n, 6).astype(np.float32)
        Qb = np.random.RandomState(n).rand(n, 4).astype(np.float32)
        assert np.array_equal(ea.predict(Qa), ref_a.predict(Qa))
        assert np.array_equal(eb.predict(Qb), ref_b.predict(Qb))
        # and vs the boosters themselves
        assert np.array_equal(ea.predict(Qa),
                              bst_a.predict(xgb.DMatrix(Qa)))
        assert np.array_equal(eb.predict(Qb),
                              bst_b.predict(xgb.DMatrix(Qb)))
    # bare resolve is the default (first-added) model
    assert cat.resolve().name == "a"
    with pytest.raises(UnknownModel) as ei:
        cat.resolve("zzz")
    assert ei.value.known == ["a", "b"]
    cat.stop()


# ------------------------------------------------------------- budget
def test_catalog_eviction_readmit_default_pinned(models, recompile_guard):
    """(b) budget enforcement: LRU eviction of the coldest non-default
    entry, on-demand re-admission, the default pinned, and the hot
    default's executables untouched by the churn."""
    _, _, Xa, _, pa, pb = models
    cat = ModelCatalog(hysteresis_sec=0.0,
                       registry_factory=_registry_factory())
    cat.add_model("d", pa)
    cat.add_model("a", pa)
    cat.add_model("b", pb)
    assert cat.default == "d"
    ed = cat.resolve("d")
    cat.resolve("a")
    time.sleep(0.01)  # strict LRU order: a older than b below
    # freeze the budget at exactly the current residency: admitting
    # one more model must evict one (the coldest non-default)
    cat.budget_bytes = cat.bytes_used()
    cat.resolve("b")
    assert not cat.get("a").resident, "coldest entry survived the budget"
    assert cat.get("b").resident and cat.get("d").resident
    assert cat.bytes_used() <= cat.budget_bytes
    time.sleep(0.01)
    # re-admission on demand; now b is the coldest and sheds
    ra = cat.resolve("a")
    assert ra.resident and not cat.get("b").resident
    assert cat.get("a").admissions == 2 and cat.get("a").evictions == 1
    # an evicted entry still advertises the hash it would serve
    assert cat.models()["b"]["hash"] == cat.get("b").last_hash is not None
    # the default survived every enforcement pass...
    assert cat.get("d").evictions == 0
    # ...and its hot engine never recompiled across the churn
    with recompile_guard.expect(0):
        ed.registry.engine.predict(Xa[:8].astype(np.float32))
    cat.stop()


def test_catalog_hysteresis_blocks_thrash(models):
    """(b) entries inside the hysteresis window are not evictable: a
    fully-hot over-budget catalog sits over budget instead of
    thrashing its working set."""
    _, _, _, _, pa, pb = models
    cat = ModelCatalog(hysteresis_sec=60.0,
                       registry_factory=_registry_factory())
    cat.add_model("a", pa)
    cat.add_model("b", pb)
    cat.resolve("a")
    cat.budget_bytes = 1  # everything is over budget now
    cat.resolve("b")
    assert cat.get("a").resident and cat.get("b").resident
    cat.stop()


# ---------------------------------------------------------------- http
def test_http_multi_model_healthz_and_404(models):
    """(c) ?model= serving, default resolution, per-model healthz rows
    with content hashes, unknown-model 404."""
    bst_a, bst_b, Xa, Xb, pa, pb = models
    srv = _catalog_replica(f"a={pa},b={pb}")
    try:
        base = f"http://127.0.0.1:{srv.port}"
        Qa, Qb = np.round(Xa[:5], 6), np.round(Xb[:5], 6)
        st, ra = _post(base + "/predict?model=a", data=_csv(Qa))
        assert st == 200 and ra["model"] == "a" and ra["rows"] == 5
        assert np.allclose(ra["predictions"],
                           bst_a.predict(xgb.DMatrix(Qa)), atol=1e-6)
        st, rb = _post(base + "/predict?model=b", data=_csv(Qb))
        assert st == 200 and rb["model"] == "b"
        assert np.allclose(rb["predictions"],
                           bst_b.predict(xgb.DMatrix(Qb)), atol=1e-6)
        # bare /predict is the default model (the catalog-of-one path)
        st, rd = _post(base + "/predict", data=_csv(Qa))
        assert st == 200 and rd["predictions"] == ra["predictions"]
        # unknown model: 404 naming the catalog, request never parsed
        st, err = _post(base + "/predict?model=zzz", data=_csv(Qa))
        assert st == 404 and err["models"] == ["a", "b"]
        h = _get(base + "/healthz")
        assert h["catalog"]["default"] == "a"
        assert h["catalog"]["configured"] == 2
        assert h["models"]["a"]["model_hash"] == _file_hash(pa)
        assert h["models"]["b"]["model_hash"] == _file_hash(pb)
        # per-model request attribution on /metrics
        mtext = urllib.request.urlopen(base + "/metrics").read().decode()
        assert 'xgbtpu_catalog_requests_total{model="b"}' in mtext
    finally:
        srv.shutdown()


# ------------------------------------------------------------- routing
def test_router_model_aware_routing(models, tmp_path):
    """(d) the router learns hosting sets from advertisements and
    dispatches ?model= only to hosting replicas."""
    bst_a, bst_b, Xa, Xb, pa, pb = models
    rt = FleetRouter(port=0, hc_sec=0, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    s1 = _catalog_replica(f"a={pa}", router_url=base, rid="r1")
    s2 = _catalog_replica(f"b={pb}", router_url=base, rid="r2")
    try:
        assert rt.membership.models_hosted() == {"a": 1, "b": 1}
        assert rt.membership.hosting("a") == {"r1"}
        Qa, Qb = np.round(Xa[:3], 6), np.round(Xb[:3], 6)
        for _ in range(3):  # every dispatch lands on the hosting replica
            st, ra = _post(base + "/predict?model=a", data=_csv(Qa))
            assert st == 200
            assert np.allclose(ra["predictions"],
                               bst_a.predict(xgb.DMatrix(Qa)), atol=1e-6)
            st, rb = _post(base + "/predict?model=b", data=_csv(Qb))
            assert st == 200
            assert np.allclose(rb["predictions"],
                               bst_b.predict(xgb.DMatrix(Qb)), atol=1e-6)
        # a model nobody hosts: 404 with the fleet's hosting map
        st, err = _post(base + "/predict?model=zzz", data=_csv(Qa))
        assert st == 404 and err["models"] == ["a", "b"]
        # by-id ownership is per (model, entity): both tenants resolve
        st, _ = _post(base + "/predict_by_id?model=a",
                      payload={"ids": ["e1"],
                               "rows": [Qa[0].tolist()]})
        assert st in (200, 404)  # 404 = featurestore disabled, routed OK
    finally:
        s1.shutdown()
        s2.shutdown()
        rt.shutdown()


def test_router_tenant_quota_isolated_shed(models):
    """(e) tenant a blowing its rate budget sheds 429; every one of
    tenant b's requests succeeds untouched."""
    bst_a, bst_b, Xa, Xb, pa, pb = models
    rt = FleetRouter(port=0, hc_sec=0, tenant_rate=1.0, tenant_burst=3.0,
                     quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    srv = _catalog_replica(f"a={pa},b={pb}", router_url=base, rid="r1")
    try:
        Qa, Qb = np.round(Xa[:2], 6), np.round(Xb[:2], 6)
        a_codes = [_post(base + "/predict?model=a", data=_csv(Qa))[0]
                   for _ in range(8)]
        assert a_codes.count(200) >= 1 and a_codes.count(429) >= 3
        # b interleaves AFTER a's bucket is drained and still succeeds
        for _ in range(3):
            st, rb = _post(base + "/predict?model=b", data=_csv(Qb))
            assert st == 200
            assert np.allclose(rb["predictions"],
                               bst_b.predict(xgb.DMatrix(Qb)), atol=1e-6)
        mtext = urllib.request.urlopen(base + "/metrics").read().decode()
        assert 'xgbtpu_tenant_shed_total{model="a"}' in mtext
        assert 'xgbtpu_tenant_shed_total{model="b"}' not in mtext
    finally:
        srv.shutdown()
        rt.shutdown()


# ---------------------------------------------------- snapshot/restore
def test_membership_snapshot_roundtrip():
    """(f) the snapshot carries identity + model advertisements; the
    restore grants fresh leases and rebuilds hosting sets."""
    m = Membership(lease_sec=30.0)
    m.register("r1", "http://h:1", model_path="/m1",
               models={"a": {"path": "/m1", "hash": "h1"}})
    m.register("r2", "http://h:2", models={"b": {"path": "/m2"}})
    m2 = Membership(lease_sec=30.0)
    assert m2.restore(m.snapshot()) == 2
    assert m2.ids() == m.ids()
    assert m2.hosting("a") == {"r1"} and m2.hosting("b") == {"r2"}
    assert m2.get("r1").url == "http://h:1"
    # garbage state restores nothing instead of raising
    assert Membership().restore({"replicas": [{"bogus": 1}]}) == 0


def test_router_restart_restores_membership(tmp_path):
    """(f) a router restarted on the same state file comes back with
    its replica set — traffic flows without any re-registration."""
    state = str(tmp_path / "fleet.state")
    rt = FleetRouter(port=0, hc_sec=0, state_path=state, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    from tests.test_fleet import _Stub
    stub = _Stub()
    try:
        st, _ = _post(base + "/fleet/register",
                      {"replica_id": "r1", "url": stub.url,
                       "models": {"a": {"path": "/m", "hash": "h"}}})
        assert st == 200 and os.path.exists(state)
        rt.shutdown()
        rt2 = FleetRouter(port=0, hc_sec=0, state_path=state,
                          quiet=True).start()
        try:
            assert rt2.membership.ids() == ["r1"]
            assert rt2.membership.hosting("a") == {"r1"}
            base2 = f"http://{rt2.host}:{rt2.port}"
            st, js = _post(base2 + "/predict", data=b"0.5")
            assert st == 200 and js["predictions"] == [0.5]
        finally:
            rt2.shutdown()
    finally:
        stub.close()


# ------------------------------------------------- per-tenant rollout
def test_per_tenant_rollout_and_rollback(models, tmp_path):
    """(g) rolling out tenant a's lane moves a's served hash; tenant
    b's stays pinned through the rollout AND the rollback."""
    bst_a, _, Xa, _, _, pb = models
    # private copies: the rollout rewrites the replica's model files
    pa2 = str(tmp_path / "a.bin")
    pb2 = str(tmp_path / "b.bin")
    bst_a.save_model(pa2)
    with open(pb, "rb") as f:
        with open(pb2, "wb") as g:
            g.write(f.read())
    hash_a0, hash_b0 = _file_hash(pa2), _file_hash(pb2)
    bst_a2, _ = _train(seed=3, rounds=2, n_features=6)
    staged = str(tmp_path / "a_next.bin")
    bst_a2.save_model(staged)
    rt = FleetRouter(port=0, hc_sec=0, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    srv = _catalog_replica(f"a={pa2},b={pb2}", router_url=base, rid="r1")
    try:
        rbase = f"http://127.0.0.1:{srv.port}"
        st, report = _post(base + "/fleet/rollout",
                           {"model_path": staged, "model": "a",
                            "soak_sec": 0.1})
        assert st == 200 and report["status"] == "ok", report
        assert report["model"] == "a"
        h = _get(rbase + "/healthz")
        assert (h["models"]["a"]["model_hash"]
                == _file_hash(staged) != hash_a0)
        assert h["models"]["b"]["model_hash"] == hash_b0, "b's lane moved"
        # targeted rollback: a returns to its prior content, b pinned
        st, rb = _post(base + "/fleet/rollback", {"model": "a"})
        assert st == 200 and rb["model"] == "a"
        h = _get(rbase + "/healthz")
        assert h["models"]["a"]["model_hash"] == hash_a0
        assert h["models"]["b"]["model_hash"] == hash_b0
        assert _file_hash(pa2) == hash_a0  # file restored too
    finally:
        srv.shutdown()
        rt.shutdown()


# ------------------------------------------------------- tenant lanes
def test_tenant_lanes_isolated(tmp_path):
    """(h) two concurrent training lanes: one misconfigured lane errors
    out; the neighbor still publishes, with its own gated ledger."""
    from xgboost_tpu.pipeline import SyntheticDataSource, run_tenant_lanes
    pub_b = str(tmp_path / "b" / "model.bin")
    os.makedirs(os.path.dirname(pub_b))
    params = {"objective": "binary:logistic", "max_depth": 2, "silent": 1}
    out = run_tenant_lanes({
        # lane a: no data source at all -> ValueError inside the lane
        "a": {"publish_path": str(tmp_path / "a" / "model.bin"),
              "workdir": str(tmp_path / "a-work"), "params": params},
        "b": {"publish_path": pub_b,
              "workdir": str(tmp_path / "b-work"),
              "source": SyntheticDataSource(n_rows=200, n_features=5),
              "rounds_per_cycle": 2, "cycles": 1, "params": params},
    }, quiet=True)
    assert out["a"]["status"] == "error"
    assert out["b"]["status"] == "ok"
    assert out["b"]["summary"]["published"] == 1
    # b's own fsync'd ledger names exactly the bytes at its publish path
    ledger = open(os.path.join(str(tmp_path / "b-work"),
                               "gated.log")).read().split()
    assert ledger[-1] == _file_hash(pub_b)
    assert not os.path.exists(str(tmp_path / "a" / "model.bin"))

"""Group-padded rank layout (round 4, rank_device.rank_gradient_padded).

The learner relays ranking entries group-padded (label-sorted rows,
lane padding per group) so the LambdaRank gradient runs sort-free and
gather-free.  These tests pin the layout invariants, the user-row
unmapping of every output surface, and trained-metric parity against
the sort-based device path (reference semantics:
src/learner/objective-inl.hpp:274-570).
"""

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.rank_device import build_pad_prep, build_prep


def make_ragged(seed=0, n_groups=50, lo=5, hi=60):
    rng = np.random.RandomState(seed)
    rows, labels, groups = [], [], []
    for _ in range(n_groups):
        n = rng.randint(lo, hi)
        Xg = rng.rand(n, 6).astype(np.float32)
        score = Xg[:, 0] * 2 + Xg[:, 1] - 0.5 * Xg[:, 2] \
            + 0.3 * rng.randn(n)
        rel = np.zeros(n, np.int32)
        order = np.argsort(-score)
        rel[order[: max(1, n // 6)]] = 2
        rel[order[max(1, n // 6): max(2, n // 3)]] = 1
        rows.append(Xg)
        labels.append(rel)
        groups.append(n)
    return (np.concatenate(rows), np.concatenate(labels).astype(np.float32),
            np.array(groups))


def test_pad_prep_invariants():
    X, y, groups = make_ragged(seed=3)
    gptr = np.concatenate([[0], np.cumsum(groups)])
    prep = build_pad_prep(y, gptr)
    G, L = prep.G, prep.L
    assert G == len(groups)
    assert L >= groups.max() and L % 8 == 0
    # pad_map / user_map are inverse on occupied slots
    occ = prep.pad_map >= 0
    assert occ.sum() == len(y)
    assert np.array_equal(prep.user_map[prep.pad_map[occ]],
                          np.nonzero(occ)[0])
    lab = np.asarray(prep.label)
    valid = np.asarray(prep.valid)
    for g in range(G):
        sz = groups[g]
        assert valid[g, :sz].all() and not valid[g, sz:].any()
        # labels descending within the group's lanes
        assert (np.diff(lab[g, :sz]) <= 0).all()
        # slot rows really belong to group g
        slot_rows = prep.pad_map[g * L: g * L + sz]
        assert ((slot_rows >= gptr[g]) & (slot_rows < gptr[g + 1])).all()
        # bucket bounds delimit equal-label runs
        b_lo = np.asarray(prep.b_lo)[g, :sz]
        b_sz = np.asarray(prep.b_sz)[g, :sz]
        for j in range(sz):
            blk = lab[g, b_lo[j]: b_lo[j] + b_sz[j]]
            assert (blk == lab[g, j]).all()
            if b_lo[j] > 0:
                assert lab[g, b_lo[j] - 1] != lab[g, j]
    # idcg matches the flat prep's
    flat = build_prep(y, gptr, len(y))
    np.testing.assert_allclose(
        np.asarray(prep.idcg)[:, 0],
        np.asarray(flat.idcg)[gptr[:-1]], rtol=1e-6)


def test_padded_entry_selected_and_unmapped():
    """The padded entry activates for device-rank training, and every
    user-facing surface (predict, pred_leaf, eval) returns USER row
    order — pinned by comparing against the same model applied to an
    uncached (plain-layout) copy of the data."""
    X, y, groups = make_ragged(seed=1)
    d = xgb.DMatrix(X, label=y, group=groups)
    bst = xgb.train({"objective": "rank:ndcg", "max_depth": 4, "eta": 0.3},
                    d, 8, verbose_eval=False)
    entry = bst._cache[id(d)]
    assert entry.rank_pad_prep is not None
    assert entry.binned.shape[0] == (entry.rank_pad_prep.G
                                     * entry.rank_pad_prep.L
                                     + entry.rank_pad_prep.n_tail)

    d2 = xgb.DMatrix(X, label=y, group=groups)  # uncached -> plain layout
    p_cached = bst.predict(d)
    p_fresh = bst.predict(d2)
    np.testing.assert_allclose(p_cached, p_fresh, rtol=1e-5, atol=1e-6)

    l_cached = bst.predict(d, pred_leaf=True)
    l_fresh = bst.predict(d2, pred_leaf=True)
    assert np.array_equal(l_cached, l_fresh)

    # eval through the padded entry == eval through a plain entry
    e_cached = bst.eval(d, "x")
    e_fresh = bst.eval(d2, "x")
    assert e_cached.split("\t")[1:] == e_fresh.split("\t")[1:]


def test_padded_boost_identical_trees():
    """boost() with user gradients scatters them into padded slots;
    padding rows carry zero gradient, so the grown trees match the
    plain layout's exactly."""
    X, y, groups = make_ragged(seed=2)
    rng = np.random.RandomState(0)
    g = rng.randn(len(y)).astype(np.float32)
    h = np.abs(rng.randn(len(y))).astype(np.float32) + 0.1

    import os
    preds = {}
    for pad in ("1", "0"):
        os.environ["XGBTPU_RANK_PAD"] = pad
        try:
            d = xgb.DMatrix(X, label=y, group=groups)
            bst = xgb.Booster({"objective": "rank:ndcg", "max_depth": 4,
                               "eta": 0.5}, cache=[d])
            bst.boost(d, g, h)
            bst.boost(d, g * 0.5, h)
            preds[pad] = bst.predict(d, output_margin=True)
        finally:
            os.environ.pop("XGBTPU_RANK_PAD", None)
    assert (bst._cache[id(d)].rank_pad_prep is None)  # pad=0 disabled it
    np.testing.assert_allclose(preds["1"], preds["0"],
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("kind,floor", [
    ("pairwise", 0.85), ("ndcg", 0.85), ("map", 0.85)])
def test_padded_matches_sort_path_quality(kind, floor):
    """Padded vs sort-based device gradients: different pair-sampling
    draws, same expected gradient — trained metrics must agree."""
    import os
    X, y, groups = make_ragged(seed=4)
    res = {}
    for pad in ("1", "0"):
        os.environ["XGBTPU_RANK_PAD"] = pad
        try:
            d = xgb.DMatrix(X, label=y, group=groups)
            r = {}
            xgb.train({"objective": f"rank:{kind}", "max_depth": 4,
                       "eta": 0.3, "eval_metric": ["ndcg"]},
                      d, 10, evals=[(d, "train")], evals_result=r,
                      verbose_eval=False)
            res[pad] = r["train-ndcg"][-1]
        finally:
            os.environ.pop("XGBTPU_RANK_PAD", None)
    assert res["1"] > floor, (kind, res)
    assert abs(res["1"] - res["0"]) < 0.05, (kind, res)


def test_padded_fused_scan_path():
    """No-evals training takes update_many's fused path with the padded
    gradient closure; set_group afterwards rebuilds the layout."""
    X, y, groups = make_ragged(seed=5)
    d = xgb.DMatrix(X, label=y, group=groups)
    bst = xgb.Booster({"objective": "rank:ndcg", "max_depth": 3,
                       "eta": 0.3}, cache=[d])
    bst.update(d, 0)
    entry = bst._cache[id(d)]
    prep = entry.rank_pad_prep
    assert prep is not None
    assert bst.obj.fused_grad(d.info, pad_prep=prep) is not None
    bst.update_many(d, 1, 5)
    p = bst.predict(d)
    assert p.shape == (len(y),)
    ndcg = float(bst.eval(d, "t").split(":")[-1])
    assert np.isfinite(ndcg)

    # layout invalidation: changing groups rebuilds the entry
    g2 = groups.copy()
    g2[0] -= 1
    g2[1] += 1
    d.set_group(g2)
    bst.update(d, 6)
    assert bst._cache[id(d)].rank_pad_prep is not prep


def test_padded_entry_invalidated_on_objective_switch():
    """set_param to a non-rank objective (or rank_impl=host) after
    padded training must rebuild a plain entry — the padded layout is
    meaningful only to the device rank gradient."""
    X, y, groups = make_ragged(seed=6, n_groups=20)
    d = xgb.DMatrix(X, label=y, group=groups)
    bst = xgb.Booster({"objective": "rank:ndcg", "max_depth": 3,
                       "eta": 0.3}, cache=[d])
    bst.update(d, 0)
    assert bst._cache[id(d)].rank_pad_prep is not None
    bst.set_param("rank_impl", "host")
    bst.update(d, 1)
    assert bst._cache[id(d)].rank_pad_prep is None
    p = bst.predict(d)
    assert p.shape == (len(y),)


def test_align_cut_lists():
    """Bin-count alignment (binning.align_cut_lists): the densest
    features trim to land B = max_cuts + 2 on the quantum; spread is
    preserved (evenly rank-spaced selection); small/aligned inputs are
    untouched."""
    from xgboost_tpu.binning import align_cut_lists, pack_cuts
    per = [np.arange(65, dtype=np.float32),
           np.arange(10, dtype=np.float32)]
    out = align_cut_lists(per, 32)
    assert pack_cuts(out).max_bin == 64
    assert len(out[0]) == 62 and len(out[1]) == 10
    # trimmed cuts remain sorted and bracket the original range
    assert out[0][0] == 0.0 and out[0][-1] == 64.0
    assert (np.diff(out[0]) > 0).all()
    # aligned input is a no-op
    per2 = [np.arange(62, dtype=np.float32)]
    assert align_cut_lists(per2, 32) is per2
    # tiny inputs are never trimmed below 8 cuts
    per3 = [np.arange(33, dtype=np.float32)]
    out3 = align_cut_lists(per3, 32)
    assert len(out3[0]) == 30  # B: 35 -> 32
    per4 = [np.arange(20, dtype=np.float32)]
    assert align_cut_lists(per4, 32) is per4  # target < 8 -> no-op
    assert align_cut_lists(per, 0) is per
    # advisor (round 4): B far above the lower multiple must NOT collapse
    # down to it — B=63 keeps all 61 cuts (1 padded sublane beats losing
    # half the resolution)...
    per5 = [np.arange(61, dtype=np.float32)]
    assert align_cut_lists(per5, 32) is per5
    # ...unless the caller explicitly lifts the cap (hist_bin_align>0)
    out5 = align_cut_lists(per5, 32, trim_margin=None)
    assert pack_cuts(out5).max_bin == 32 and len(out5[0]) == 30
    # boundary: excess == margin still trims
    per6 = [np.arange(66, dtype=np.float32)]   # B = 68, excess 4
    assert pack_cuts(align_cut_lists(per6, 32)).max_bin == 64


def test_hist_bin_align_param_plumbing():
    """hist_bin_align > 0 forces alignment regardless of backend (the
    CPU scatter path ignores tiling but must honor the explicit knob);
    0 disables; the model records the aligned cuts."""
    rng = np.random.RandomState(0)
    X = rng.randn(40_000, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    b1 = xgb.Booster({"objective": "binary:logistic", "max_depth": 3,
                      "hist_bin_align": 32}, cache=[d])
    b1.update(d, 0)
    assert b1.gbtree.cfg.n_bin % 32 == 0
    d2 = xgb.DMatrix(X, label=y)
    b2 = xgb.Booster({"objective": "binary:logistic", "max_depth": 3,
                      "hist_bin_align": 0}, cache=[d2])
    b2.update(d2, 0)
    assert b2.gbtree.cfg.n_bin >= b1.gbtree.cfg.n_bin


def test_padded_gate_declines_large_lanes():
    """A single huge group exceeds the lane cap -> sort path."""
    import os
    rng = np.random.RandomState(0)
    n = 2000
    X = rng.rand(n, 4).astype(np.float32)
    y = (rng.rand(n) * 3).astype(np.int32).astype(np.float32)
    d = xgb.DMatrix(X, label=y, group=[n])  # one group of 2000 > 256 lanes
    bst = xgb.Booster({"objective": "rank:ndcg", "max_depth": 3}, cache=[d])
    bst.update(d, 0)
    assert bst._cache[id(d)].rank_pad_prep is None
    assert os.environ.get("XGBTPU_RANK_PAD") is None


def test_rank_path_announcement():
    """The first boosting round prints exactly one `[rank] LambdaRank
    gradient path:` stderr line naming the choice for the TRAINING
    matrix (README 'Ranking'); XGBTPU_RANK_PAD=0 flips it to
    sort-based; silent=1 mutes (advisor, round 4)."""
    import contextlib
    import io
    import os
    import numpy as np
    import xgboost_tpu as xgb

    rng = np.random.RandomState(0)
    n, G = 1000, 50
    X = rng.rand(n, 6).astype(np.float32)
    y = rng.randint(0, 4, n).astype(np.float32)

    def run(silent=0):
        d = xgb.DMatrix(X, label=y)
        d.set_group(np.full(G, n // G, np.int64))
        dv = xgb.DMatrix(X[:200], label=y[:200])
        dv.set_group(np.full(10, 20, np.int64))
        err = io.StringIO()
        with contextlib.redirect_stderr(err):
            xgb.train({"objective": "rank:ndcg", "max_depth": 3,
                       "eta": 0.3, "silent": silent}, d, 2,
                      evals=[(dv, "val")], verbose_eval=False)
        return [l for l in err.getvalue().splitlines()
                if l.startswith("[rank] LambdaRank gradient path")]

    lines = run()
    assert len(lines) == 1 and "group-padded" in lines[0], lines
    os.environ["XGBTPU_RANK_PAD"] = "0"
    try:
        lines = run()
    finally:
        del os.environ["XGBTPU_RANK_PAD"]
    assert len(lines) == 1 and "sort-based" in lines[0], lines
    assert run(silent=1) == []

"""End-to-end training tests — the reference's demo configs as integration
tests (SURVEY.md §4: demo/binary_classification mushroom.conf, regression,
custom objective path)."""

import numpy as np
import pytest

import xgboost_tpu as xgb

AGARICUS_TRAIN = "/root/reference/demo/data/agaricus.txt.train"
AGARICUS_TEST = "/root/reference/demo/data/agaricus.txt.test"


@pytest.fixture(scope="module")
def agaricus():
    dtrain = xgb.DMatrix(AGARICUS_TRAIN)
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=dtrain.num_col)
    return dtrain, dtest


def test_agaricus_mushroom_conf(agaricus):
    """Reference demo/binary_classification/mushroom.conf: eta=1.0,
    max_depth=3, 2 rounds, binary:logistic -> train error ~0.0141,
    test error ~0.0162 (printed by the reference demo)."""
    dtrain, dtest = agaricus
    params = {"eta": 1.0, "max_depth": 3, "objective": "binary:logistic",
              "eval_metric": "error"}
    res = {}
    bst = xgb.train(params, dtrain, 2, evals=[(dtrain, "train"),
                                              (dtest, "test")],
                    evals_result=res, verbose_eval=False)
    assert res["train-error"][-1] < 0.02
    assert res["test-error"][-1] < 0.02
    preds = bst.predict(dtest)
    assert preds.shape == (dtest.num_row,)
    assert preds.min() >= 0.0 and preds.max() <= 1.0
    err = np.mean((preds > 0.5) != (dtest.get_label() == 1))
    assert err < 0.02


def test_agaricus_deeper_converges(agaricus):
    dtrain, dtest = agaricus
    params = {"eta": 0.3, "max_depth": 6, "objective": "binary:logistic"}
    res = {}
    xgb.train(params, dtrain, 10, evals=[(dtest, "test")], evals_result=res,
              verbose_eval=False)
    assert res["test-error"][-1] < 0.005  # agaricus is nearly separable


def test_regression_squared_error():
    rng = np.random.RandomState(0)
    X = rng.rand(2000, 5).astype(np.float32)
    y = (3 * X[:, 0] + np.sin(5 * X[:, 1]) + 0.1 * rng.randn(2000)).astype(
        np.float32)
    dtrain = xgb.DMatrix(X[:1500], label=y[:1500])
    dtest = xgb.DMatrix(X[1500:], label=y[1500:])
    params = {"objective": "reg:linear", "max_depth": 4, "eta": 0.3,
              "base_score": 0.5}
    res = {}
    xgb.train(params, dtrain, 40, evals=[(dtest, "test")], evals_result=res,
              verbose_eval=False)
    # residual noise floor is 0.1; a working booster gets close
    assert res["test-rmse"][-1] < 0.25
    assert res["test-rmse"][-1] < res["test-rmse"][0] * 0.3


def test_eval_line_format(agaricus):
    dtrain, dtest = agaricus
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3},
                    dtrain, 1, verbose_eval=False)
    line = bst.eval_set([(dtrain, "train"), (dtest, "eval")], 7)
    assert line.startswith("[7]\ttrain-error:")
    assert "\teval-error:" in line


def test_predict_margin_vs_transform(agaricus):
    dtrain, _ = agaricus
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3},
                    dtrain, 2, verbose_eval=False)
    margin = bst.predict(dtrain, output_margin=True)
    prob = bst.predict(dtrain)
    np.testing.assert_allclose(prob, 1 / (1 + np.exp(-margin)), rtol=1e-5)


def test_custom_objective(agaricus):
    """Custom obj path == reference Booster.boost / XGBoosterBoostOneIter
    (demo/guide-python/custom_objective.py)."""
    dtrain, dtest = agaricus

    def logregobj(preds, dtrain):
        labels = dtrain.get_label()
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - labels, p * (1.0 - p)

    def evalerror(preds, dmat):
        labels = dmat.get_label()
        return "error", float(np.mean((preds > 0.0) != (labels == 1)))

    params = {"max_depth": 2, "eta": 1.0, "objective": "binary:logitraw"}
    res = {}
    xgb.train(params, dtrain, 3, evals=[(dtest, "test")], obj=logregobj,
              feval=evalerror, evals_result=res, verbose_eval=False)
    assert res["test-error"][-1] < 0.05


def test_early_stopping(agaricus):
    dtrain, dtest = agaricus
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 1.0,
              "eval_metric": "logloss"}
    bst = xgb.train(params, dtrain, 50, evals=[(dtest, "test")],
                    early_stopping_rounds=3, verbose_eval=False)
    assert bst.best_iteration >= 0
    assert bst.best_score < 0.1


def test_profile_round_breakdown(agaricus, capsys):
    """profile=1 emits per-round phase timing + summary (SURVEY.md §5.1
    report_stats analog) without changing results."""
    dtrain, dtest = agaricus
    params = {"eta": 1.0, "max_depth": 3, "objective": "binary:logistic"}
    p_plain = xgb.train(params, dtrain, 2, verbose_eval=False).predict(dtest)
    bst = xgb.train({**params, "profile": 1}, xgb.DMatrix(AGARICUS_TRAIN), 2,
                    evals=[(dtest, "eval")], verbose_eval=False)
    err = capsys.readouterr().err
    assert "[prof] round 0:" in err and "grow=" in err
    assert "[prof]   grow" in err and "ms/round" in err
    assert "eval" in err
    prof = bst._profiler
    assert len(prof.rounds) == 2
    assert all("grow" in r["phases"] for r in prof.rounds)
    np.testing.assert_allclose(bst.predict(dtest), p_plain,
                               rtol=1e-5, atol=1e-6)


def test_weights_affect_training():
    rng = np.random.RandomState(1)
    X = rng.rand(500, 3).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    w = np.where(y == 1, 10.0, 0.1).astype(np.float32)
    dtrain = xgb.DMatrix(X, label=y, weight=w)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 2},
                    dtrain, 5, verbose_eval=False)
    preds = bst.predict(dtrain)
    # heavily weighted positives should be predicted confidently
    assert preds[y == 1].mean() > 0.8


def test_base_margin(agaricus):
    """boost_from_prediction demo: margin continuation must equal training
    longer (demo/guide-python/boost_from_prediction.py)."""
    dtrain, _ = agaricus
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.5}
    bst1 = xgb.train(params, dtrain, 4, verbose_eval=False)
    m1 = bst1.predict(dtrain, output_margin=True)

    bst_a = xgb.train(params, dtrain, 2, verbose_eval=False)
    ptrain = bst_a.predict(dtrain, output_margin=True)
    dtrain2 = xgb.DMatrix(AGARICUS_TRAIN)
    dtrain2.set_base_margin(ptrain)
    bst_b = xgb.train(params, dtrain2, 2, verbose_eval=False)
    m2 = bst_b.predict(dtrain2, output_margin=True)
    # same data/params: two-stage margins should be very close to one-shot
    assert np.abs(m1 - m2).mean() < 0.5


def test_subsample_colsample(agaricus):
    dtrain, dtest = agaricus
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.5,
              "subsample": 0.7, "colsample_bytree": 0.7,
              "colsample_bylevel": 0.8, "seed": 3}
    res = {}
    xgb.train(params, dtrain, 8, evals=[(dtest, "test")], evals_result=res,
              verbose_eval=False)
    assert res["test-error"][-1] < 0.05


def test_determinism(agaricus):
    dtrain, _ = agaricus
    params = {"objective": "binary:logistic", "max_depth": 4, "subsample": 0.8,
              "seed": 7}
    p1 = xgb.train(params, dtrain, 3, verbose_eval=False).predict(dtrain)
    p2 = xgb.train(params, dtrain, 3, verbose_eval=False).predict(dtrain)
    np.testing.assert_array_equal(p1, p2)


def test_scale_pos_weight_survives_model_reload(tmp_path):
    """Continued training after load_model must keep objective-side params
    (scale_pos_weight et al.) that live in the saved header."""
    rng = np.random.RandomState(3)
    X = rng.rand(500, 5).astype(np.float32)
    y = (X[:, 0] > 0.8).astype(np.float32)  # imbalanced
    d = xgb.DMatrix(X, label=y)
    params = {"objective": "binary:logistic", "scale_pos_weight": 5.0,
              "max_depth": 3, "eta": 0.3}
    bst = xgb.train(params, d, 2, verbose_eval=False)
    path = str(tmp_path / "spw.model")
    bst.save_model(path)

    bst2 = xgb.Booster(model_file=path)
    assert bst2.obj.scale_pos_weight == 5.0
    bst2.update(d, 2)  # continued training uses the weighted gradient
    bst_ref = xgb.train(params, xgb.DMatrix(X, label=y), 3,
                        verbose_eval=False)
    np.testing.assert_allclose(bst2.predict(d),
                               bst_ref.predict(xgb.DMatrix(X, label=y)),
                               rtol=2e-4, atol=2e-5)


def test_vmapped_ensemble_bit_matches_sequential(monkeypatch):
    """VERDICT r1 item 6: K x num_parallel_tree trees grow in one vmapped
    launch; the stacked result must bit-match the sequential path."""
    import os
    import xgboost_tpu as xgb

    rng = np.random.RandomState(5)
    X = rng.rand(600, 6).astype(np.float32)
    y = (X[:, 0] * 3).astype(int) % 3
    params = {"objective": "multi:softmax", "num_class": 3, "max_depth": 3,
              "eta": 0.4, "num_parallel_tree": 2, "max_bin": 16,
              "subsample": 0.8, "gamma": 0.1}

    monkeypatch.setenv("XGBTPU_SEQ_BOOST", "1")
    b_seq = xgb.train(params, xgb.DMatrix(X, label=y), 3, verbose_eval=False)
    monkeypatch.delenv("XGBTPU_SEQ_BOOST")
    b_vm = xgb.train(params, xgb.DMatrix(X, label=y), 3, verbose_eval=False)

    s_seq, s_vm = b_seq.gbtree.get_state(), b_vm.gbtree.get_state()
    assert set(s_seq) == set(s_vm)
    for k in s_seq:
        np.testing.assert_array_equal(s_seq[k], s_vm[k], err_msg=k)


def test_vmapped_ensemble_bit_matches_sequential_dp(monkeypatch):
    """Same bit-match under the dsplit=row mesh path."""
    import xgboost_tpu as xgb

    rng = np.random.RandomState(6)
    X = rng.rand(500, 5).astype(np.float32)
    y = (X[:, 0] * 3).astype(int) % 3
    params = {"objective": "multi:softmax", "num_class": 3, "max_depth": 3,
              "eta": 0.4, "max_bin": 16, "dsplit": "row"}

    monkeypatch.setenv("XGBTPU_SEQ_BOOST", "1")
    b_seq = xgb.train(params, xgb.DMatrix(X, label=y), 3, verbose_eval=False)
    monkeypatch.delenv("XGBTPU_SEQ_BOOST")
    b_vm = xgb.train(params, xgb.DMatrix(X, label=y), 3, verbose_eval=False)

    s_seq, s_vm = b_seq.gbtree.get_state(), b_vm.gbtree.get_state()
    for k in s_seq:
        np.testing.assert_array_equal(s_seq[k], s_vm[k], err_msg=k)


def test_gblinear_converges_on_correlated_features():
    """Round-1 verdict weak item 7: fully-parallel Jacobi diverges on
    strongly correlated features; the block-sequential CD (default
    linear_block=1) must converge even on perfectly duplicated columns."""
    import xgboost_tpu as xgb

    rng = np.random.RandomState(0)
    base = rng.rand(500, 1).astype(np.float32)
    X = np.repeat(base, 16, axis=1)  # 16 identical columns
    y = (2.0 * base[:, 0] + 0.1 * rng.randn(500)).astype(np.float32)
    res = {}
    xgb.train({"booster": "gblinear", "objective": "reg:linear",
               "eta": 0.5, "lambda": 1.0}, xgb.DMatrix(X, label=y), 30,
              evals=[(xgb.DMatrix(X, label=y), "train")],
              evals_result=res, verbose_eval=False)
    r = [float(v) for v in res["train-rmse"]]
    assert np.isfinite(r[-1]) and r[-1] < r[0] and r[-1] < 0.15, r[-1]

"""External-memory training tests (reference
demo/guide-python/external_memory.py + page_dmatrix-inl.hpp)."""

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.external import ExtMemDMatrix


def make_data(n=3000, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = ((X[:, 0] > 0.5) ^ (X[:, 1] > 0.3)).astype(np.float32)
    return X, y


def chunked(X, y, size):
    for s in range(0, len(X), size):
        yield X[s:s + size], y[s:s + size]


def write_svm(path, X, y):
    with open(path, "w") as f:
        for row, lab in zip(X, y):
            feats = " ".join(f"{j}:{v:.6f}" for j, v in enumerate(row))
            f.write(f"{lab:g} {feats}\n")


PARAMS = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.5}


def test_ext_single_page_matches_in_ram(tmp_path, monkeypatch):
    """With one page the streaming sketch equals the in-RAM sketch, so
    paged training must reproduce the in-RAM model exactly."""
    monkeypatch.setenv("XGTPU_EXT_DEVICE_CACHE_MB", "0")  # force streaming
    X, y = make_data()
    d_ram = xgb.DMatrix(X, label=y)
    bst_ram = xgb.train(PARAMS, d_ram, 5, verbose_eval=False)

    d_ext = ExtMemDMatrix(chunked(X, y, len(X)),
                          cache=str(tmp_path / "c1"), page_rows=len(X))
    bst_ext = xgb.train(PARAMS, d_ext, 5, verbose_eval=False)

    p_ram = bst_ram.predict(d_ram)
    p_ext = bst_ext.predict(d_ext)
    np.testing.assert_allclose(p_ram, p_ext, rtol=2e-4, atol=2e-5)


def test_ext_multi_page_training(tmp_path, monkeypatch):
    """Many small pages: batch-accumulated histograms must train well;
    eval/predict stream batches."""
    monkeypatch.setenv("XGTPU_EXT_DEVICE_CACHE_MB", "0")  # force streaming
    X, y = make_data(n=5000)
    d_ext = ExtMemDMatrix(chunked(X, y, 256), cache=str(tmp_path / "c2"),
                          page_rows=512)
    assert d_ext.num_row == 5000 and d_ext.num_col == 10
    res = {}
    bst = xgb.train(PARAMS, d_ext, 8, evals=[(d_ext, "train")],
                    evals_result=res, verbose_eval=False)
    assert res["train-error"][-1] < 0.05
    preds = bst.predict(d_ext)
    assert preds.shape == (5000,)
    leaves = bst.predict(d_ext, pred_leaf=True)
    assert leaves.shape == (5000, 8)


def test_ext_eval_on_separate_matrix(tmp_path, monkeypatch):
    monkeypatch.setenv("XGTPU_EXT_DEVICE_CACHE_MB", "0")  # force streaming
    X, y = make_data(n=4000, seed=1)
    d_tr = ExtMemDMatrix(chunked(X[:3000], y[:3000], 500),
                         cache=str(tmp_path / "tr"), page_rows=512)
    d_te = ExtMemDMatrix(chunked(X[3000:], y[3000:], 500),
                         cache=str(tmp_path / "te"), page_rows=512)
    res = {}
    xgb.train(PARAMS, d_tr, 6, evals=[(d_te, "test")], evals_result=res,
              verbose_eval=False)
    assert res["test-error"][-1] < 0.1


def test_ext_from_libsvm_and_cli(tmp_path):
    X, y = make_data(n=1200, f=6, seed=2)
    svm = tmp_path / "train.svm"
    write_svm(svm, X, y)

    d = ExtMemDMatrix(f"{svm}#{tmp_path / 'cc'}")
    assert d.num_row == 1200 and d.num_col == 6
    bst = xgb.train(PARAMS, d, 4, verbose_eval=False)
    err = ((bst.predict(d) > 0.5) != (y > 0.5)).mean()
    assert err < 0.1

    # CLI ext: scheme
    from xgboost_tpu.cli import main as cli_main
    model = str(tmp_path / "ext.model")
    conf = tmp_path / "ext.conf"
    conf.write_text(
        f"task = train\nobjective = binary:logistic\nmax_depth = 3\n"
        f"eta = 0.5\nnum_round = 3\ndata = ext:{svm}#{tmp_path / 'cc2'}\n"
        f"model_out = {model}\nsilent = 1\n")
    assert cli_main([str(conf)]) == 0
    import os
    assert os.path.exists(model)


def test_ext_continue_and_gamma(tmp_path):
    X, y = make_data(n=2000, seed=3)
    d = ExtMemDMatrix(chunked(X, y, 400), cache=str(tmp_path / "g"),
                      page_rows=512)
    bst = xgb.train({**PARAMS, "gamma": 0.5}, d, 3, verbose_eval=False)
    n_before = bst.gbtree.num_trees
    bst2 = xgb.train({**PARAMS, "gamma": 0.5}, d, 2, xgb_model=bst,
                     verbose_eval=False)
    assert bst2.gbtree.num_trees == n_before + 2


def test_ext_slice_unsupported(tmp_path):
    X, y = make_data(n=100)
    d = ExtMemDMatrix(chunked(X, y, 50), cache=str(tmp_path / "s"))
    with pytest.raises(NotImplementedError):
        d.slice(np.arange(10))


def test_ext_custom_objective(tmp_path, monkeypatch):
    """Custom-objective (fobj) training over a paged matrix."""
    monkeypatch.setenv("XGTPU_EXT_DEVICE_CACHE_MB", "0")  # force streaming
    X, y = make_data(n=1500, seed=5)
    d = ExtMemDMatrix(chunked(X, y, 300), cache=str(tmp_path / "co"),
                      page_rows=512)

    def logistic_obj(preds, dmat):
        labels = dmat.get_label()
        return preds - labels, preds * (1.0 - preds)

    bst = xgb.train(PARAMS, d, 3, obj=logistic_obj, verbose_eval=False)
    err = ((bst.predict(d) > 0.5) != (y > 0.5)).mean()
    assert err < 0.15


def test_ext_predict_across_models_rebinned(tmp_path):
    """A matrix binned by model A must be re-quantized when model B
    (different cuts) predicts on it."""
    X, y = make_data(n=800, seed=6)
    Xb, yb = make_data(n=800, seed=7)
    d = ExtMemDMatrix(chunked(X, y, 200), cache=str(tmp_path / "ra"))
    bst_a = xgb.train(PARAMS, d, 3, verbose_eval=False)
    p_a = bst_a.predict(d)

    d_other = xgb.DMatrix(Xb * 10.0, label=yb)  # very different value range
    bst_b = xgb.train({**PARAMS, "max_bin": 16}, d_other, 3,
                      verbose_eval=False)
    # B's one-off prediction re-bins with B's cuts...
    p_b = bst_b.predict(d)
    assert p_b.shape == (800,)
    # ...and must not corrupt A's view (A re-bins back on next use)
    np.testing.assert_allclose(bst_a.predict(d), p_a, rtol=1e-5)


def test_ext_colsample_changes_model(tmp_path):
    X, y = make_data(n=1000, seed=8)
    d1 = ExtMemDMatrix(chunked(X, y, 250), cache=str(tmp_path / "f1"))
    d2 = ExtMemDMatrix(chunked(X, y, 250), cache=str(tmp_path / "f2"))
    bst_full = xgb.train(PARAMS, d1, 2, verbose_eval=False)
    bst_cs = xgb.train({**PARAMS, "colsample_bytree": 0.3, "seed": 9},
                       d2, 2, verbose_eval=False)
    f_full = {int(f) for t in bst_full.gbtree.trees
              for f in np.asarray(t.feature) if f >= 0}
    f_cs = {int(f) for t in bst_cs.gbtree.trees
            for f in np.asarray(t.feature) if f >= 0}
    assert f_cs != f_full or len(f_cs) < len(f_full)


def test_ext_distributed_row_split_single_shard_bit_identical(tmp_path, monkeypatch):
    """Distributed external memory (VERDICT r1 item 5), mechanics check:
    on a 1-device mesh the shard_map+psum path must reproduce the
    single-chip paged model bit-for-bit (no reduction-order noise)."""
    monkeypatch.setenv("XGTPU_EXT_DEVICE_CACHE_MB", "0")  # force streaming
    from xgboost_tpu.parallel.mesh import data_parallel_mesh, set_mesh

    X, y = make_data(n=2000)
    d1 = ExtMemDMatrix(chunked(X, y, 300), cache=str(tmp_path / "s"),
                       page_rows=512)
    bst1 = xgb.train(PARAMS, d1, 5, verbose_eval=False)

    set_mesh(data_parallel_mesh(1))
    try:
        d2 = ExtMemDMatrix(chunked(X, y, 300), cache=str(tmp_path / "d"),
                           page_rows=512)
        bst2 = xgb.train({**PARAMS, "dsplit": "row"}, d2, 5,
                         verbose_eval=False)
    finally:
        set_mesh(None)

    s1, s2 = bst1.gbtree.get_state(), bst2.gbtree.get_state()
    for k in s1:
        np.testing.assert_array_equal(s1[k], s2[k], err_msg=k)


def test_ext_distributed_row_split_8way_quality(tmp_path, monkeypatch):
    """8-way sharded paged training: psum reduction order may flip
    near-tie splits (true of the reference's allreduce too), so the bar
    is model QUALITY parity with the single-chip paged run."""
    monkeypatch.setenv("XGTPU_EXT_DEVICE_CACHE_MB", "0")  # force streaming
    X, y = make_data(n=2000)
    d1 = ExtMemDMatrix(chunked(X, y, 300), cache=str(tmp_path / "s8"),
                       page_rows=512)
    r1 = {}
    xgb.train(PARAMS, d1, 5, evals=[(d1, "train")], evals_result=r1,
              verbose_eval=False)

    d2 = ExtMemDMatrix(chunked(X, y, 300), cache=str(tmp_path / "d8"),
                       page_rows=512)
    r2 = {}
    xgb.train({**PARAMS, "dsplit": "row"}, d2, 5, evals=[(d2, "train")],
              evals_result=r2, verbose_eval=False)
    e1, e2 = float(r1["train-error"][-1]), float(r2["train-error"][-1])
    assert abs(e1 - e2) <= 0.01, (e1, e2)


def test_half_ram_variant_matches_paged(tmp_path):
    """HalfRAM ('!' prefix, reference DMatrixHalfRAM io.cpp:70-73): raw
    rows paged on disk, binned matrix in RAM — must train identically to
    the memmap-backed paged matrix."""
    X, y = make_data(n=1500, f=6, seed=3)
    svm = tmp_path / "hr.svm"
    write_svm(svm, X, y)

    d_page = ExtMemDMatrix(f"{svm}#{tmp_path / 'p'}")
    d_half = xgb.DMatrix(f"!{svm}#{tmp_path / 'h'}")  # DMatrix URI route
    assert isinstance(d_half, ExtMemDMatrix) and d_half.half_ram
    b1 = xgb.train(PARAMS, d_page, 4, verbose_eval=False)
    b2 = xgb.train(PARAMS, d_half, 4, verbose_eval=False)
    np.testing.assert_allclose(np.asarray(b1.predict(d_page)),
                               np.asarray(b2.predict(d_half)),
                               rtol=1e-6, atol=1e-7)
    # HalfRAM keeps the binned matrix off disk
    import os
    assert not os.path.exists(str(tmp_path / "h") + ".binned")


def test_dmatrix_ext_uri_route(tmp_path):
    """DMatrix('ext:path#cache') constructs the paged matrix."""
    X, y = make_data(n=800, f=5, seed=4)
    svm = tmp_path / "u.svm"
    write_svm(svm, X, y)
    d = xgb.DMatrix(f"ext:{svm}#{tmp_path / 'u'}")
    assert isinstance(d, ExtMemDMatrix) and not d.half_ram
    assert d.num_row == 800 and d.num_col == 5


def test_ext_in_budget_collapses_to_in_memory_path(tmp_path, monkeypatch):
    """An in-budget paged matrix takes the in-memory fast path (one
    launch per tree) and must produce the same-quality model as the
    forced-streaming path — and an identical model to a plain DMatrix
    on the same rows."""
    X, y = make_data(n=2500, seed=9)
    d_stream = ExtMemDMatrix(chunked(X, y, 300),
                             cache=str(tmp_path / "s"), page_rows=512)
    monkeypatch.setenv("XGTPU_EXT_DEVICE_CACHE_MB", "0")
    b_stream = xgb.train(PARAMS, d_stream, 5, verbose_eval=False)
    monkeypatch.delenv("XGTPU_EXT_DEVICE_CACHE_MB")

    d_fast = ExtMemDMatrix(chunked(X, y, 300),
                           cache=str(tmp_path / "f"), page_rows=512)
    b_fast = xgb.train(PARAMS, d_fast, 5, verbose_eval=False)
    bst = b_fast._cache  # entry should be non-external (in-memory path)
    entry = bst[id(d_fast)]
    assert not entry.external and entry.binned is not None

    # same pages, same (streaming-sketch) cuts: the in-budget fast path
    # must match the forced-streaming path very closely (only histogram
    # accumulation order differs)
    d_ram = xgb.DMatrix(X, label=y)
    p1 = np.asarray(b_fast.predict(d_ram))
    p2 = np.asarray(b_stream.predict(d_ram))
    np.testing.assert_allclose(p1, p2, rtol=2e-4, atol=2e-5)
    err = ((p1 > 0.5) != (y > 0.5)).mean()
    assert err < 0.1, err


def test_prefetch_to_device_exception_and_early_close():
    """The streaming prefetcher (external._prefetch_to_device) must
    relay producer exceptions to the consumer and retire its worker
    thread when the consumer stops early (round 5)."""
    import threading

    from xgboost_tpu.external import _prefetch_to_device

    # normal drain preserves order and content
    batches = [(i, np.full((4,), i, np.uint8)) for i in range(5)]
    got = list(_prefetch_to_device(iter(batches)))
    assert [s for s, _ in got] == [0, 1, 2, 3, 4]
    for (_, a), (_, b) in zip(batches, got):
        np.testing.assert_array_equal(a, np.asarray(b))

    # producer exception surfaces at the consumer
    def bad():
        yield 0, np.zeros(4, np.uint8)
        raise RuntimeError("disk gone")

    it = _prefetch_to_device(bad())
    next(it)
    with pytest.raises(RuntimeError, match="disk gone"):
        next(it)

    # early close joins the worker (no leaked alive threads)
    before = {t.ident for t in threading.enumerate()}
    it = _prefetch_to_device(iter(batches))
    next(it)
    it.close()
    leaked = [t for t in threading.enumerate()
              if t.ident not in before and t.is_alive()]
    assert not leaked, leaked

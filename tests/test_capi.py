"""C ABI (native/xgtpu_capi.c + xgboost_tpu/capi_bridge.py).

Builds the shared library and a pure-C driver program, then runs the
driver as a REAL non-Python host: train agaricus through the C API,
eval, predict, save/load round-trip, dump.  The reference's analogous
surface is wrapper/xgboost_wrapper.cpp:113-353.
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "native")
TRAIN = "/root/reference/demo/data/agaricus.txt.train"
TEST = "/root/reference/demo/data/agaricus.txt.test"

pytestmark = pytest.mark.skipif(
    shutil.which("gcc") is None or shutil.which("make") is None,
    reason="no C toolchain")


@pytest.fixture(scope="module")
def capi_lib():
    r = subprocess.run(["make", "-C", NATIVE, "capi"], capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    return os.path.join(NATIVE, "libxgboost_tpu.so")


@pytest.fixture(scope="module")
def demo_bin(capi_lib, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("capi") / "capi_demo")
    r = subprocess.run(
        ["gcc", "-O2", "-o", out, os.path.join(REPO, "tests", "capi_demo.c"),
         f"-I{NATIVE}", f"-L{NATIVE}", "-lxgboost_tpu",
         f"-Wl,-rpath,{NATIVE}"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    return out


@pytest.mark.skipif(not os.path.exists(TRAIN), reason="no agaricus data")
def test_c_host_end_to_end(demo_bin, tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # the axon TPU plugin can re-register itself over JAX_PLATFORMS in
    # the driver's embedded Python; pin the histogram impl so the
    # prediction-parity check below compares the SAME numerics (the
    # TPU default is int8-quantized histograms — ~7e-4 off the CPU
    # scatter, fine for training, not for a 5e-5 equality assert)
    env["XGBTPU_HIST"] = "scatter"
    r = subprocess.run([demo_bin, TRAIN, TEST, str(tmp_path / "m.model")],
                       capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    out = r.stdout
    assert "C-ABI-OK" in out
    assert "rows train=6513 test=1611" in out
    # the exact round-0 error of the reference demo config
    assert "train-error:0.014433" in out
    assert "roundtrip=identical" in out
    assert "dump trees=2 first_node_ok=1" in out
    # predictions parity with the Python API on the same config
    import xgboost_tpu as xgb
    dtrain = xgb.DMatrix(TRAIN)
    dtest = xgb.DMatrix(TEST, num_col=dtrain.num_col)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 1.0}, dtrain, 2, verbose_eval=False)
    want = float(np.asarray(bst.predict(dtest))[0])
    got = float(out.split("pred0=")[1].split()[0])
    # the C driver runs in its own process (different XLA flag set than
    # the 8-virtual-device conftest here), so float summation order may
    # differ in the last bits; %g printing adds ~1e-6 quantization
    assert abs(got - want) < 5e-5

"""xgboost_tpu.stream — streaming, drift-aware continuous learning.

Acceptance criteria covered here (ISSUE 15; the subprocess SIGKILL
variant lives in ``tools/chaos_loop.py --stream`` → STREAM_CHAOS.json):

(a) drift score semantics: same-distribution cycles score ≈ 0, an
    injected shift fires, and fire/clear hysteresis triggers exactly
    ONE refresh per drift episode;
(b) streaming source determinism: the first ``next_cycle(k)`` commits
    a manifest and every later call — including from a brand-new
    source object over the same directory, after MORE batches arrived
    — replays identical bytes; backpressure and the state machine;
(c) sliding-holdout window semantics: ``holdout_for(k)`` is the
    previous ``holdout_cycles`` cycles' batches, one distinct object
    per cycle index (the incumbent-score cache keys on identity);
(d) online cut refresh bit-parity at zero drift: rebinding the SAME
    cuts leaves model bytes identical, and rebinding refreshed
    (sketch ∪ live-threshold) cuts moves no decision boundary;
(e) EMA-FS off-knob bit-identity at K ∈ {1, 16} fused segments, and
    screened training restricted to the kept feature set;
(f) the StreamTrainer end-to-end: plans committed per cycle, drift →
    refresh on real shifted data, and a fresh-workdir replay over the
    same stream directory publishing the identical model sequence.
"""

import hashlib
import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import xgboost_tpu as xgb  # noqa: E402
from xgboost_tpu.binning import CutMatrix  # noqa: E402
from xgboost_tpu.learner import Booster  # noqa: E402
from xgboost_tpu.stream import (FeatureDriftTracker,  # noqa: E402
                                StreamBacklogFull, StreamDataSource,
                                live_thresholds_of, propose_refreshed_cuts,
                                run_stream, summarize_columns)

PARAMS = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.4,
          "silent": 1}


def make_rows(n, f=6, shift=0.0, seed=0):
    rng = np.random.RandomState(seed)
    X = (rng.rand(n, f) + shift).astype(np.float32)
    y = ((X[:, 0] + 0.25 * X[:, 1]) > (0.6 + 1.25 * shift)).astype(
        np.float32)
    return X, y


# ----------------------------------------------------------------- drift
def test_drift_score_no_drift_near_zero():
    t = FeatureDriftTracker(4, threshold=0.25)
    for c in range(4):
        X, _ = make_rows(500, f=4, seed=c)
        t.observe_cycle(summarize_columns(X))
        st = t.step()
        assert st["max_score"] < 0.1, st
        assert not st["fired"] and not st["refresh"]


def test_drift_score_shift_fires():
    t = FeatureDriftTracker(4, threshold=0.25, window=2)
    for c in range(3):
        X, _ = make_rows(500, f=4, seed=c)
        t.observe_cycle(summarize_columns(X))
        t.step()
    X, _ = make_rows(500, f=4, shift=3.0, seed=10)
    t.observe_cycle(summarize_columns(X))
    st = t.step()
    assert st["max_score"] > 0.25
    assert st["fired"] and st["refresh"]


def test_drift_hysteresis_one_refresh_per_episode():
    """Scores oscillating above `clear` after a fire re-trigger
    NOTHING; a refresh edge only returns after the state cleared."""
    t = FeatureDriftTracker(2, threshold=0.25, clear=0.1, window=1)
    X, _ = make_rows(800, f=2, seed=0)
    t.observe_cycle(summarize_columns(X))
    t.step()
    refreshes = 0
    for c in range(4):  # sustained shift: stays fired, no re-refresh
        Xs, _ = make_rows(800, f=2, shift=2.0, seed=20 + c)
        t.observe_cycle(summarize_columns(Xs))
        st = t.step()
        assert st["fired"]
        refreshes += int(st["refresh"])
    assert refreshes == 1
    # back to the ORIGINAL distribution -> clears (reference never
    # rebased in this test), then a second shift refires
    for c in range(2):
        Xb, _ = make_rows(800, f=2, seed=40 + c)
        t.observe_cycle(summarize_columns(Xb))
        st = t.step()
    assert not st["fired"]
    Xs, _ = make_rows(800, f=2, shift=2.0, seed=60)
    t.observe_cycle(summarize_columns(Xs))
    st = t.step()
    assert st["refresh"]


def test_drift_tracker_roundtrip():
    t = FeatureDriftTracker(3, threshold=0.2, clear=0.05, window=2)
    for c in range(3):
        X, _ = make_rows(300, f=3, seed=c)
        t.observe_cycle(summarize_columns(X))
        t.step()
    t2 = FeatureDriftTracker.from_arrays(t.to_arrays())
    assert t2.fired == t.fired and t2.threshold == t.threshold
    np.testing.assert_allclose(t2.scores(), t.scores())


# ---------------------------------------------------------------- source
def test_stream_source_manifests_deterministic(tmp_path):
    s = StreamDataSource(str(tmp_path / "st"), min_batches=2,
                         max_batches=3)
    for i in range(4):
        s.push(*make_rows(50, seed=i))
    out = s.next_cycle(0)
    assert out is not None
    d0, _ = out
    names0 = s.batches_for(0)
    assert len(names0) == 3  # max_batches bite
    # more batches arrive; the committed cycle must NOT re-decide
    for i in range(3):
        s.push(*make_rows(50, seed=10 + i))
    assert s.batches_for(0) == names0
    # a brand-new source over the same dir replays identical bytes
    s2 = StreamDataSource(str(tmp_path / "st"), min_batches=2,
                          max_batches=3)
    X0, y0 = s.read_cycle_arrays(0)
    X0b, y0b = s2.read_cycle_arrays(0)
    np.testing.assert_array_equal(X0, X0b)
    np.testing.assert_array_equal(y0, y0b)


def test_stream_source_states_and_backpressure(tmp_path):
    s = StreamDataSource(str(tmp_path / "st"), min_batches=2,
                         max_batches=2, catchup_backlog=4, max_backlog=5)
    assert s.next_cycle(0) is None
    assert s.state == "idle"
    s.push(*make_rows(10))
    assert s.next_cycle(0) is None
    assert s.state == "collecting"
    for i in range(4):
        s.push(*make_rows(10, seed=i + 1))
    assert s.next_cycle(0) is not None
    assert s.state == "catch_up"  # 5 unclaimed >= catchup_backlog 4
    assert s.next_cycle(1) is not None
    assert s.state == "ready"
    # backlog now 1 (5 - 2*2); cap is 5 -> 4 more pushes fill it
    for i in range(4):
        s.push(*make_rows(10, seed=20 + i))
    with pytest.raises(StreamBacklogFull):
        s.push(*make_rows(10, seed=99))


def test_stream_sliding_holdout_window(tmp_path):
    s = StreamDataSource(str(tmp_path / "st"), min_batches=1,
                         max_batches=1, holdout_cycles=2)
    for i in range(4):
        s.push(*make_rows(30, seed=i))
    for c in range(4):
        assert s.next_cycle(c) is not None
    # holdout for cycle 3 = cycles {1, 2}'s batches, in order
    h3 = s.holdout_for(3)
    y_expect = np.concatenate([s.read_cycle_arrays(1)[1],
                               s.read_cycle_arrays(2)[1]])
    np.testing.assert_array_equal(np.asarray(h3.get_label()), y_expect)
    # one distinct object per cycle index, memoized within a cycle
    assert s.holdout_for(3) is h3
    assert s.holdout_for(2) is not h3
    # cycle 0 (no history) judges on its own batches
    h0 = s.holdout_for(0)
    np.testing.assert_array_equal(np.asarray(h0.get_label()),
                                  s.read_cycle_arrays(0)[1])


# ----------------------------------------------------------- cut refresh
def _train(params, screen=None, k=4, rounds=6, f=10):
    rng = np.random.RandomState(0)
    X = rng.rand(800, f).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0.6).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    b = Booster(dict(params), cache=[d])
    if screen is not None:
        b.set_feature_screen(screen)
    b.update_many(d, 0, rounds, rounds_per_dispatch=k)
    return b, d, X


def test_rebind_identical_cuts_byte_identity():
    """Zero drift degenerates to rebinding the same cuts: model bytes
    must not move."""
    b, d, _ = _train(PARAMS)
    raw = bytes(b.save_raw())
    preds = np.asarray(b.predict(d))
    cuts = b.gbtree.cuts
    b.rebind_cuts(CutMatrix(np.array(cuts.cut_values),
                            np.array(cuts.n_cuts)))
    assert bytes(b.save_raw()) == raw
    np.testing.assert_array_equal(np.asarray(b.predict(d)), preds)


def test_rebind_refreshed_cuts_moves_no_boundary():
    """Sketch-proposal ∪ live-threshold cuts from a SHIFTED
    distribution: every prediction bit-matches, and training continues
    on the new binning."""
    b, d, X = _train(PARAMS)
    preds = np.asarray(b.predict(d))
    cuts = propose_refreshed_cuts(
        summarize_columns(X * 1.7 + 0.3),
        live_thresholds_of(b.gbtree, X.shape[1]), 64)
    b.rebind_cuts(cuts)
    np.testing.assert_array_equal(np.asarray(b.predict(d)), preds)
    b.update_many(d, 6, 2, rounds_per_dispatch=2)
    assert np.isfinite(np.asarray(b.predict(d))).all()


def test_rebind_missing_threshold_raises():
    b, d, X = _train(PARAMS)
    from xgboost_tpu.binning import compute_cuts
    # cuts built WITHOUT the live thresholds: rebind must refuse
    # (loudly) rather than silently move split boundaries
    alien = compute_cuts(xgb.DMatrix(X * 3.0 + 11.0), max_bin=8)
    with pytest.raises(ValueError, match="absent from the new cuts"):
        b.rebind_cuts(alien)


# --------------------------------------------------------------- EMA-FS
@pytest.mark.parametrize("k", [1, 16])
def test_ema_fs_off_bit_identity(k):
    """ema_fs=0 (the default) ignores any installed screen: model
    bytes and predictions bit-match a run without the knob, at fused
    segment sizes 1 and 16."""
    b0, d, _ = _train(PARAMS, k=k)
    b1, _, _ = _train({**PARAMS, "ema_fs": 0.0}, screen=[0, 1, 2], k=k)
    assert bytes(b0.save_raw()) == bytes(b1.save_raw())
    np.testing.assert_array_equal(np.asarray(b0.predict(d)),
                                  np.asarray(b1.predict(d)))


def test_ema_fs_on_restricts_working_set():
    kept = [0, 1, 5]
    b, d, _ = _train({**PARAMS, "ema_fs": 0.9, "ema_fs_min_features": 2},
                     screen=kept)
    used = set()
    for t in b.gbtree.trees:
        f = np.asarray(t.feature)
        used |= set(int(i) for i in f[f >= 0])
    assert used <= set(kept), used
    assert np.isfinite(np.asarray(b.predict(d))).all()


def test_set_feature_screen_validates():
    b = Booster(dict(PARAMS))
    with pytest.raises(ValueError):
        b.set_feature_screen([])
    with pytest.raises(ValueError):
        b.set_feature_screen([-1, 2])
    b.set_feature_screen([2, 0, 2])
    assert b._feature_screen == (0, 2)
    b.set_feature_screen(None)
    assert b._feature_screen is None


# ----------------------------------------------------------- end-to-end
def _push_cycles(src, n_cycles, batches_per_cycle=2, shift=0.0, seed0=0):
    for i in range(n_cycles * batches_per_cycle):
        src.push(*make_rows(120, shift=shift, seed=seed0 + i))


def _file_hash(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _gated(workdir):
    try:
        with open(os.path.join(workdir, "gated.log")) as f:
            return [ln.split()[1] for ln in f if len(ln.split()) >= 2]
    except OSError:
        return []


def test_stream_trainer_end_to_end_with_drift(tmp_path):
    src = StreamDataSource(str(tmp_path / "st"), min_batches=2,
                           max_batches=2)
    _push_cycles(src, 2)
    publish = str(tmp_path / "model.bin")
    wd = str(tmp_path / "wd")
    kw = dict(source=src, rounds_per_cycle=2, max_regression=10.0,
              sleep_sec=0.0, params=dict(PARAMS), quiet=True)
    summary = run_stream(publish, workdir=wd, cycles=2, **kw)
    assert summary["published"] == 2 and summary["errors"] == 0
    plans = [json.load(open(os.path.join(wd, "plans", "plan-%06d.json"
                                         % c))) for c in (0, 1)]
    assert not plans[0]["refresh"] and not plans[1]["refresh"]
    # shifted stream: the next cycle must fire drift and refresh cuts
    _push_cycles(src, 1, shift=3.0, seed0=50)
    summary = run_stream(publish, workdir=wd, cycles=1, **kw)
    assert summary["published"] == 1 and summary["errors"] == 0
    plan2 = json.load(open(os.path.join(wd, "plans",
                                        "plan-000002.json")))
    assert plan2["fired"] and plan2["refresh"]
    assert os.path.exists(os.path.join(wd, "plans", "cuts-000002.npz"))
    # the published model still loads + predicts after the refresh
    bst = xgb.Booster(model_file=publish)
    X, _ = make_rows(64, shift=3.0, seed=99)
    assert np.isfinite(np.asarray(bst.predict(xgb.DMatrix(X)))).all()


def test_stream_replay_publishes_identical_sequence(tmp_path):
    """A FRESH workdir over the same stream directory re-derives the
    identical gated-model sequence — the manifest determinism the
    chaos harness's replay check rests on."""
    src = StreamDataSource(str(tmp_path / "st"), min_batches=1,
                           max_batches=2)
    _push_cycles(src, 3)
    kw = dict(rounds_per_cycle=2, cycles=3, max_regression=10.0,
              sleep_sec=0.0, params=dict(PARAMS), quiet=True)
    run_stream(str(tmp_path / "a.bin"), workdir=str(tmp_path / "wa"),
               source=src, **kw)
    src_b = StreamDataSource(str(tmp_path / "st"), min_batches=1,
                             max_batches=2)
    run_stream(str(tmp_path / "b.bin"), workdir=str(tmp_path / "wb"),
               source=src_b, **kw)
    a, b = _gated(str(tmp_path / "wa")), _gated(str(tmp_path / "wb"))
    assert a and a == b
    assert _file_hash(str(tmp_path / "a.bin")) == _file_hash(
        str(tmp_path / "b.bin"))


def test_stream_ema_fs_screen_applies_across_cycles(tmp_path):
    """With ema_fs on, cycles after the first train against a reduced
    feature working set (the EMA needs one cycle of gains first)."""
    src = StreamDataSource(str(tmp_path / "st"), min_batches=1,
                           max_batches=1)
    _push_cycles(src, 3, batches_per_cycle=1)
    params = {**PARAMS, "ema_fs": 0.8, "ema_fs_min_features": 2}
    wd = str(tmp_path / "wd")
    summary = run_stream(str(tmp_path / "m.bin"), workdir=wd,
                         source=src, rounds_per_cycle=2, cycles=3,
                         max_regression=10.0, sleep_sec=0.0,
                         params=params, quiet=True)
    assert summary["published"] == 3 and summary["errors"] == 0
    plans = [json.load(open(os.path.join(wd, "plans",
                                         "plan-%06d.json" % c)))
             for c in range(3)]
    assert plans[0]["kept"] is None  # no gain history yet
    kept = plans[2]["kept"]
    assert kept is not None and 2 <= len(kept) < 6

"""Reference binary model format: reader + cross-check fixtures.

The committed fixtures in ``tests/data/`` were produced by the reference
CLI built from ``/root/reference`` (demo/binary_classification
mushroom.conf, 2 rounds, depth 3):

- ``ref_agaricus.model``  — ``binf`` binary model (with pred buffer)
- ``ref_agaricus.bs64``   — the same model in base64 text mode
  (``model_out=stdout``)
- ``ref_agaricus.pred``   — the reference CLI's own predictions on
  agaricus.txt.test (``%g`` precision)

so the round-trip bar is: load the reference's bytes, predict, match the
reference's numbers (SURVEY.md M2).
"""

import os

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.compat import load_reference_model, parse_reference_model

DATA = os.path.join(os.path.dirname(__file__), "data")
AGARICUS_TEST = "/root/reference/demo/data/agaricus.txt.test"
AGARICUS_TRAIN = "/root/reference/demo/data/agaricus.txt.train"


@pytest.fixture(scope="module")
def ref_model_path():
    return os.path.join(DATA, "ref_agaricus.model")


def test_parse_reference_model(ref_model_path):
    with open(ref_model_path, "rb") as f:
        parsed = parse_reference_model(f.read())
    assert parsed["objective"] == "binary:logistic"
    assert parsed["gbm"] == "gbtree"
    assert parsed["num_feature"] == 126
    assert len(parsed["trees"]) == 2
    assert list(parsed["tree_info"]) == [0, 0]
    nodes, stats = parsed["trees"][0]
    assert (nodes["cleft"] == -1).sum() > 0          # has leaves
    assert (stats["sum_hess"] > 0).all()


def test_reference_model_predictions_match(ref_model_path):
    """Predictions from the loaded reference model must equal the
    reference CLI's own pred output."""
    bst = load_reference_model(ref_model_path)
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=126)
    preds = bst.predict(dtest)
    ref = np.loadtxt(os.path.join(DATA, "ref_agaricus.pred"))
    assert preds.shape == ref.shape
    np.testing.assert_allclose(preds, ref, rtol=1e-4, atol=1e-5)


def test_reference_bs64_matches_binf():
    b1 = load_reference_model(os.path.join(DATA, "ref_agaricus.model"))
    b2 = load_reference_model(os.path.join(DATA, "ref_agaricus.bs64"))
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=126)
    np.testing.assert_array_equal(b1.predict(dtest), b2.predict(dtest))


def test_booster_load_model_autodetects_reference(ref_model_path):
    """Booster(model_file=...) must transparently read reference files."""
    bst = xgb.Booster(model_file=ref_model_path)
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=126)
    ref = np.loadtxt(os.path.join(DATA, "ref_agaricus.pred"))
    np.testing.assert_allclose(bst.predict(dtest), ref, rtol=1e-4, atol=1e-5)


def test_save_base64_roundtrip(tmp_path):
    """Our own bs64 text-safe mode: save -> load -> bit-identical preds,
    and the file must be single-line printable text after the magic."""
    dtrain = xgb.DMatrix(AGARICUS_TRAIN)
    bst = xgb.train({"eta": 1.0, "max_depth": 3,
                     "objective": "binary:logistic"}, dtrain, 2,
                    verbose_eval=False)
    p = str(tmp_path / "m.bs64")
    bst.save_model(p, save_base64=True)
    with open(p, "rb") as f:
        raw = f.read()
    assert raw[:5] == b"bs64\t"
    body = raw[5:].rstrip(b"\n")
    assert all(32 <= c < 127 for c in body)  # survives text channels
    bst2 = xgb.Booster(model_file=p)
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=dtrain.num_col)
    np.testing.assert_array_equal(bst.predict(dtest), bst2.predict(dtest))


def test_cli_save_base64(tmp_path):
    """CLI save_base64=1 writes the text-safe encoding."""
    from xgboost_tpu.cli import BoostLearnTask
    model = str(tmp_path / "cli.bs64")
    rc = BoostLearnTask().run([
        f"data={AGARICUS_TRAIN}", "num_round=1", "max_depth=3",
        "objective=binary:logistic", "silent=2", "save_base64=1",
        f"model_out={model}"])
    assert rc == 0
    with open(model, "rb") as f:
        assert f.read(5) == b"bs64\t"
    bst = xgb.Booster(model_file=model)
    assert bst.predict(xgb.DMatrix(AGARICUS_TEST, num_col=126)).shape == (1611,)


# ----------------------------------------------------------------- writer

def test_reference_writer_self_roundtrip(tmp_path):
    """save_reference_model -> our own reference reader reproduces the
    predictions exactly (format-level self-consistency)."""
    from xgboost_tpu.compat import save_reference_model

    dtrain = xgb.DMatrix(AGARICUS_TRAIN)
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=dtrain.num_col)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 1.0}, dtrain, 2, verbose_eval=False)
    want = np.asarray(bst.predict(dtest))

    path = str(tmp_path / "ours.refmodel")
    raw = save_reference_model(bst, path)
    assert raw[:4] == b"binf"
    # loads through the generic loader (magic autodetect)
    b2 = xgb.Booster(model_file=path)
    got = np.asarray(b2.predict(xgb.DMatrix(AGARICUS_TEST,
                                            num_col=dtrain.num_col)))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)

    # bs64 text mode round-trips too
    raw64 = save_reference_model(bst, str(tmp_path / "ours.bs64"),
                                 base64_mode=True)
    assert raw64.startswith(b"bs64\t")
    b3 = xgb.Booster(model_file=str(tmp_path / "ours.bs64"))
    got64 = np.asarray(b3.predict(xgb.DMatrix(AGARICUS_TEST,
                                              num_col=dtrain.num_col)))
    np.testing.assert_allclose(got64, want, rtol=1e-6, atol=1e-7)


def test_reference_writer_multiclass_and_linear(tmp_path):
    from xgboost_tpu.compat import save_reference_model

    rng = np.random.RandomState(0)
    X = rng.rand(300, 5).astype(np.float32)
    y = (X[:, 0] * 3).astype(int).clip(0, 2).astype(np.float32)
    bst = xgb.train({"objective": "multi:softmax", "num_class": 3,
                     "max_depth": 3, "eta": 0.5},
                    xgb.DMatrix(X, label=y), 3, verbose_eval=False)
    p = save_reference_model(bst, str(tmp_path / "mc.refmodel"))
    b2 = xgb.Booster(model_file=str(tmp_path / "mc.refmodel"))
    np.testing.assert_allclose(b2.predict(xgb.DMatrix(X)),
                               bst.predict(xgb.DMatrix(X)),
                               rtol=1e-6, atol=1e-7)
    # the prediction-buffer sections are num_pbuffer * num_output_group
    # entries EACH (gbtree-inl.hpp PredBufferSize) — a K=1-shaped counter
    # would make multiclass models unreadable by reference tooling
    from xgboost_tpu.compat import _GBTREE_PARAM, _LEARNER_PARAM
    raw = p[4:]  # skip binf
    off = _LEARNER_PARAM.size
    from xgboost_tpu.compat import _read_str
    _, off = _read_str(raw, off)
    _, off = _read_str(raw, off)
    _, _, _, npb, nog, _ = _GBTREE_PARAM.unpack_from(raw, off)
    assert npb == 300 and nog == 3
    assert raw.endswith(b"\x00" * (8 * 300 * 3))  # buffer + counter

    yl = (X[:, 0] > 0.5).astype(np.float32)
    bl = xgb.train({"booster": "gblinear", "objective": "binary:logistic",
                    "eta": 0.5}, xgb.DMatrix(X, label=yl), 4,
                   verbose_eval=False)
    save_reference_model(bl, str(tmp_path / "lin.refmodel"))
    b3 = xgb.Booster(model_file=str(tmp_path / "lin.refmodel"))
    np.testing.assert_allclose(b3.predict(xgb.DMatrix(X)),
                               bl.predict(xgb.DMatrix(X)),
                               rtol=1e-5, atol=1e-6)


@pytest.fixture(scope="module")
def reference_cli():
    """The reference C++ CLI, built from /root/reference (cached)."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from tools.parity import build_reference
        return build_reference("/tmp/xgbtpu_parity")
    except Exception as e:  # no compiler / build breakage
        pytest.skip(f"reference binary unavailable: {e}")


def test_reference_cli_consumes_our_model(tmp_path, reference_cli):
    """THE round-trip (VERDICT r2 item 6): a model trained HERE, saved in
    the reference format, fed to the reference CLI ``task=pred`` —
    its predictions must match ours on agaricus."""
    import subprocess
    from xgboost_tpu.compat import save_reference_model

    dtrain = xgb.DMatrix(AGARICUS_TRAIN)
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=dtrain.num_col)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 1.0}, dtrain, 2, verbose_eval=False)
    ours = np.asarray(bst.predict(dtest))

    model = str(tmp_path / "ours.refmodel")
    save_reference_model(bst, model)
    conf = tmp_path / "pred.conf"
    conf.write_text("task = pred\n")
    pred_out = str(tmp_path / "pred.txt")
    r = subprocess.run(
        [reference_cli, str(conf), f"model_in={model}",
         f"test:data={AGARICUS_TEST}", f"name_pred={pred_out}",
         "use_buffer=0", "silent=1"],
        capture_output=True, text=True, timeout=300, cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr[-2000:]
    ref_pred = np.loadtxt(pred_out)
    assert ref_pred.shape == ours.shape
    # the reference prints %g (6 significant digits)
    np.testing.assert_allclose(ref_pred, ours, rtol=2e-5, atol=2e-6)

    # CONTINUED TRAINING with the default prediction buffer: the writer
    # bakes num_pbuffer = our cached rows + a zeroed buffer, matching
    # what the reference itself writes.  (Adding NEW eval sets at
    # continue time overflows num_pbuffer for reference-trained models
    # too — a brittleness of the format, verified, not of this writer.)
    tconf = tmp_path / "cont.conf"
    tconf.write_text("task = train\n")
    cont = str(tmp_path / "cont.model")
    r2 = subprocess.run(
        [reference_cli, str(tconf), f"data={AGARICUS_TRAIN}",
         "objective=binary:logistic", "max_depth=3", "eta=1.0",
         "num_round=1", f"model_in={model}", "silent=1",
         f"model_out={cont}"],
        capture_output=True, text=True, timeout=300, cwd=str(tmp_path))
    assert r2.returncode == 0, (r2.stdout + r2.stderr)[-2000:]
    # the continued model loads back here and extends the ensemble
    b3 = xgb.Booster(model_file=cont)
    assert b3.gbtree.num_trees == bst.gbtree.num_trees + 1


def test_exact_colmaker_matches_reference_splits(tmp_path, reference_cli):
    """TRUE exact mode (VERDICT r2 item 5): on a continuous dataset with
    ~50k distinct values per feature — far past the old 4096-bin cap —
    our grow_colmaker must match the reference CLI's exact greedy
    split-for-split (same features, same gains) and prediction-for-
    prediction."""
    import subprocess
    rng = np.random.RandomState(5)
    N = 50_000
    X = rng.randn(N, 3).astype(np.float32)  # ~N distinct values/feature
    y = ((X[:, 0] > 0.3) ^ (X[:, 1] < -0.2)).astype(np.float32)
    train = tmp_path / "exact.train"
    with open(train, "w") as f:
        for i in range(N):
            f.write(f"{y[i]:g} " + " ".join(
                f"{j}:{X[i, j]:.6f}" for j in range(3)) + "\n")

    conf = tmp_path / "t.conf"
    conf.write_text("task = train\n")
    ref_model = str(tmp_path / "ref.model")
    r = subprocess.run(
        [reference_cli, str(conf), f"data={train}",
         "objective=binary:logistic", "max_depth=3", "eta=0.5",
         "num_round=2", "use_buffer=0", "silent=1",
         f"model_out={ref_model}"],
        capture_output=True, text=True, timeout=600, cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr[-2000:]

    d = xgb.DMatrix(str(train))
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 0.5, "updater": "grow_colmaker,prune"},
                    d, 2, verbose_eval=False)
    assert bst.gbtree.exact_raw

    # the DISTRIBUTED exact path (dsplit=col, round 5) bit-matches the
    # single-device model, so the split-for-split check below covers it
    # transitively — asserted here against the same reference run
    d_col = xgb.DMatrix(str(train))
    bst_col = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                         "eta": 0.5, "updater": "grow_colmaker,prune",
                         "dsplit": "col"}, d_col, 2, verbose_eval=False)
    assert bst_col.gbtree.exact_raw
    assert bst_col.get_dump() == bst.get_dump()

    # split-for-split on SIGNAL nodes (gain > 20 on 50k rows): both
    # sides' float accumulation orders differ in the last bits, so
    # near-zero-gain noise nodes can legitimately tie-break apart
    parsed = parse_reference_model(open(ref_model, "rb").read())
    n_checked = 0
    for t, (nodes, stats) in enumerate(parsed["trees"]):
        ours = bst.gbtree.trees[t]
        of = np.asarray(ours.feature)
        og = np.asarray(ours.gain)
        frontier = [(0, 0)]  # (reference nid, our slot)
        while frontier:
            nid, slot = frontier.pop()
            if nodes["cleft"][nid] == -1 or stats["loss_chg"][nid] <= 20:
                continue
            rf = int(nodes["sindex"][nid] & 0x7FFFFFFF)
            assert of[slot] == rf, (t, slot, of[slot], rf)
            np.testing.assert_allclose(og[slot], stats["loss_chg"][nid],
                                       rtol=2e-3, atol=1e-3)
            n_checked += 1
            frontier.append((int(nodes["cleft"][nid]), 2 * slot + 1))
            frontier.append((int(nodes["cright"][nid]), 2 * slot + 2))
    assert n_checked >= 6, n_checked  # both trees' signal structure

    # prediction-for-prediction on the training data (noise-leaf drift
    # bounded by eta * small weights)
    ref_loaded = xgb.Booster(model_file=ref_model)
    p_ours = np.asarray(bst.predict(d))
    p_ref = np.asarray(ref_loaded.predict(d))
    assert float(np.abs(p_ours - p_ref).mean()) < 1e-3
    assert float(np.abs(p_ours - p_ref).max()) < 0.05

"""Reference binary model format: reader + cross-check fixtures.

The committed fixtures in ``tests/data/`` were produced by the reference
CLI built from ``/root/reference`` (demo/binary_classification
mushroom.conf, 2 rounds, depth 3):

- ``ref_agaricus.model``  — ``binf`` binary model (with pred buffer)
- ``ref_agaricus.bs64``   — the same model in base64 text mode
  (``model_out=stdout``)
- ``ref_agaricus.pred``   — the reference CLI's own predictions on
  agaricus.txt.test (``%g`` precision)

so the round-trip bar is: load the reference's bytes, predict, match the
reference's numbers (SURVEY.md M2).
"""

import os

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.compat import load_reference_model, parse_reference_model

DATA = os.path.join(os.path.dirname(__file__), "data")
AGARICUS_TEST = "/root/reference/demo/data/agaricus.txt.test"
AGARICUS_TRAIN = "/root/reference/demo/data/agaricus.txt.train"


@pytest.fixture(scope="module")
def ref_model_path():
    return os.path.join(DATA, "ref_agaricus.model")


def test_parse_reference_model(ref_model_path):
    with open(ref_model_path, "rb") as f:
        parsed = parse_reference_model(f.read())
    assert parsed["objective"] == "binary:logistic"
    assert parsed["gbm"] == "gbtree"
    assert parsed["num_feature"] == 126
    assert len(parsed["trees"]) == 2
    assert list(parsed["tree_info"]) == [0, 0]
    nodes, stats = parsed["trees"][0]
    assert (nodes["cleft"] == -1).sum() > 0          # has leaves
    assert (stats["sum_hess"] > 0).all()


def test_reference_model_predictions_match(ref_model_path):
    """Predictions from the loaded reference model must equal the
    reference CLI's own pred output."""
    bst = load_reference_model(ref_model_path)
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=126)
    preds = bst.predict(dtest)
    ref = np.loadtxt(os.path.join(DATA, "ref_agaricus.pred"))
    assert preds.shape == ref.shape
    np.testing.assert_allclose(preds, ref, rtol=1e-4, atol=1e-5)


def test_reference_bs64_matches_binf():
    b1 = load_reference_model(os.path.join(DATA, "ref_agaricus.model"))
    b2 = load_reference_model(os.path.join(DATA, "ref_agaricus.bs64"))
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=126)
    np.testing.assert_array_equal(b1.predict(dtest), b2.predict(dtest))


def test_booster_load_model_autodetects_reference(ref_model_path):
    """Booster(model_file=...) must transparently read reference files."""
    bst = xgb.Booster(model_file=ref_model_path)
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=126)
    ref = np.loadtxt(os.path.join(DATA, "ref_agaricus.pred"))
    np.testing.assert_allclose(bst.predict(dtest), ref, rtol=1e-4, atol=1e-5)


def test_save_base64_roundtrip(tmp_path):
    """Our own bs64 text-safe mode: save -> load -> bit-identical preds,
    and the file must be single-line printable text after the magic."""
    dtrain = xgb.DMatrix(AGARICUS_TRAIN)
    bst = xgb.train({"eta": 1.0, "max_depth": 3,
                     "objective": "binary:logistic"}, dtrain, 2,
                    verbose_eval=False)
    p = str(tmp_path / "m.bs64")
    bst.save_model(p, save_base64=True)
    with open(p, "rb") as f:
        raw = f.read()
    assert raw[:5] == b"bs64\t"
    body = raw[5:].rstrip(b"\n")
    assert all(32 <= c < 127 for c in body)  # survives text channels
    bst2 = xgb.Booster(model_file=p)
    dtest = xgb.DMatrix(AGARICUS_TEST, num_col=dtrain.num_col)
    np.testing.assert_array_equal(bst.predict(dtest), bst2.predict(dtest))


def test_cli_save_base64(tmp_path):
    """CLI save_base64=1 writes the text-safe encoding."""
    from xgboost_tpu.cli import BoostLearnTask
    model = str(tmp_path / "cli.bs64")
    rc = BoostLearnTask().run([
        f"data={AGARICUS_TRAIN}", "num_round=1", "max_depth=3",
        "objective=binary:logistic", "silent=2", "save_base64=1",
        f"model_out={model}"])
    assert rc == 0
    with open(model, "rb") as f:
        assert f.read(5) == b"bs64\t"
    bst = xgb.Booster(model_file=model)
    assert bst.predict(xgb.DMatrix(AGARICUS_TEST, num_col=126)).shape == (1611,)

"""xgtpu-lint: rule self-tests, enforcement over the real tree, and the
dynamic checkers (ANALYSIS.md).

Three layers:

1. **fixture snippets** — every rule XGT001–XGT007 fires on a known-bad
   snippet and is silenced by ``# xgtpu: disable=...`` (the suppression
   machinery is itself under test);
2. **enforcement** — the analyzer runs over the whole ``xgboost_tpu``
   package and must report ZERO unsuppressed, non-baselined findings
   (this is the tier-1 gate the ISSUE demands: conventions became
   invariants);
3. **dynamic checkers** — a seeded race proves ``LockRaceChecker``
   catches an unguarded mutation and a lock-order inversion, and
   ``RecompileGuard`` flags a steady-state recompile from XLA's own
   telemetry (the serving-scale reproduction lives in
   ``tests/test_serving.py``).

Pure-CPU AST work plus tiny jit programs — no mesh/AxisType gating
needed anywhere here.
"""

import json
import os
import threading

import pytest

from xgboost_tpu.analysis import (Baseline, analyze_source,
                                  default_baseline_path, run)
from xgboost_tpu.analysis.__main__ import main as lint_main
from xgboost_tpu.analysis.runtime import LockRaceChecker, RecompileGuard

PKG_DIR = os.path.dirname(os.path.abspath(__import__(
    "xgboost_tpu").__file__))


def codes(src, path="xgboost_tpu/models/gbtree.py"):
    """Rule codes firing on a snippet (default path: a hot-path file so
    path-scoped rules apply)."""
    active, _ = analyze_source(src, path=path)
    return sorted({f.rule for f in active})


def suppressed_codes(src, path="xgboost_tpu/models/gbtree.py"):
    _, sup = analyze_source(src, path=path)
    return sorted({f.rule for f in sup})


# ------------------------------------------------------------ rule fixtures
class TestRuleFixtures:
    """Each rule fires on its known-bad snippet, stays quiet on the good
    twin, and is silenced by an inline suppression."""

    def test_xgt001_jit_in_loop(self):
        bad = ("import jax\n"
               "def f(xs):\n"
               "    for x in xs:\n"
               "        y = jax.jit(lambda a: a + 1)(x)\n")
        assert "XGT001" in codes(bad)
        ok = ("import jax\n"
              "g = jax.jit(lambda a: a + 1)\n"
              "def f(xs):\n"
              "    return [g(x) for x in xs]\n")
        assert "XGT001" not in codes(ok)

    def test_xgt001_shape_branch_in_jit(self):
        bad = ("import jax\n"
               "@jax.jit\n"
               "def f(x):\n"
               "    if x.shape[0] > 4:\n"
               "        return x * 2\n"
               "    return x\n")
        assert "XGT001" in codes(bad)
        # the same branch on a STATIC argument is the documented fix
        ok = ("import jax, functools\n"
              "@functools.partial(jax.jit, static_argnames=('n',))\n"
              "def f(x, n):\n"
              "    if n > 4:\n"
              "        return x * 2\n"
              "    return x\n")
        assert "XGT001" not in codes(ok)

    def test_xgt001_loop_varying_slice(self):
        bad = ("import jax\n"
               "g = jax.jit(lambda a: a.sum())\n"
               "def f(x, n):\n"
               "    out = []\n"
               "    for i in range(n):\n"
               "        out.append(g(x[:i]))\n"
               "    return out\n")
        assert "XGT001" in codes(bad)

    def test_xgt002_item_in_hot_loop(self):
        bad = ("def grow(nodes, gains):\n"
               "    for nid in nodes:\n"
               "        g = gains[nid].item()\n")
        assert "XGT002" in codes(bad)
        # cold-path file: same code, rule scoped out
        assert "XGT002" not in codes(bad, path="xgboost_tpu/learner.py")
        # outside a loop: one sync, not per-iteration
        ok = ("def finalize(gains):\n"
              "    return gains.sum().item()\n")
        assert "XGT002" not in codes(ok)

    def test_xgt002_asarray_in_hot_loop(self):
        bad = ("import numpy as np\n"
               "def levels(hists):\n"
               "    while True:\n"
               "        h = np.asarray(hists[0])\n"
               "        break\n")
        assert "XGT002" in codes(bad)

    def test_xgt003_plain_write(self):
        bad = ("def save(path, data):\n"
               "    with open(path, 'w') as f:\n"
               "        f.write(data)\n")
        assert "XGT003" in codes(bad, path="xgboost_tpu/foo.py")
        # append mode is the event-log contract — exempt
        ok = ("def append(path, line):\n"
              "    with open(path, 'ab') as f:\n"
              "        f.write(line)\n")
        assert "XGT003" not in codes(ok, path="xgboost_tpu/foo.py")
        # reads never flagged
        ok2 = "def load(path):\n    return open(path).read()\n"
        assert "XGT003" not in codes(ok2, path="xgboost_tpu/foo.py")

    def test_xgt003_pathlib_and_attribute_open(self):
        bad = ("from pathlib import Path\n"
               "def save(p, data):\n"
               "    with Path(p).open('w') as f:\n"
               "        f.write(data)\n")
        assert "XGT003" in codes(bad, path="xgboost_tpu/foo.py")
        # read-mode attribute opens (fsspec streaming) never flag
        ok = ("def fetch(fs, uri):\n"
              "    with fs.open(uri, 'rb') as src:\n"
              "        return src.read()\n")
        assert "XGT003" not in codes(ok, path="xgboost_tpu/foo.py")
        # a path literal is not a mode: open(p) positional stays quiet
        ok2 = "data = open('out.txt').read()\n"
        assert "XGT003" not in codes(ok2, path="xgboost_tpu/foo.py")

    def test_xgt003_kept_tempfile(self):
        bad = ("import tempfile\n"
               "def emit(script):\n"
               "    with tempfile.NamedTemporaryFile('w', delete=False)"
               " as f:\n"
               "        f.write(script)\n"
               "        return f.name\n")
        assert "XGT003" in codes(bad, path="xgboost_tpu/foo.py")

    def test_xgt004_silent_swallow(self):
        bad = ("def f():\n"
               "    try:\n"
               "        g()\n"
               "    except Exception:\n"
               "        pass\n")
        assert "XGT004" in codes(bad)
        for ok in (
                # re-raise
                "def f():\n    try:\n        g()\n"
                "    except Exception:\n        raise\n",
                # the fix recipe: counted
                "def f():\n    try:\n        g()\n"
                "    except Exception as e:\n"
                "        swallowed_error('site', e)\n",
                # narrow except
                "def f():\n    try:\n        g()\n"
                "    except OSError:\n        pass\n",
                # surfaced via the exception name
                "def f():\n    try:\n        g()\n"
                "    except Exception as e:\n        h(e)\n"):
            assert "XGT004" not in codes(ok), ok

    def test_xgt005_unguarded_mutation(self):
        bad = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self.n = 0\n"
               "    def inc(self):\n"
               "        with self._lock:\n"
               "            self.n += 1\n"
               "    def reset(self):\n"
               "        self.n = 0\n")
        assert "XGT005" in codes(bad)
        # *_locked naming convention: caller holds the lock
        ok = bad.replace("def reset(self):", "def reset_locked(self):")
        assert "XGT005" not in codes(ok)
        # __init__ construction is single-threaded: never flagged
        ok2 = bad.replace("    def reset(self):\n        self.n = 0\n", "")
        assert "XGT005" not in codes(ok2)

    def test_xgt006_wallclock_duration(self):
        bad = ("import time\n"
               "def f():\n"
               "    t0 = time.time()\n"
               "    g()\n"
               "    return time.time() - t0\n")
        assert "XGT006" in codes(bad)
        # timestamps (no subtraction) are the documented exemption
        ok = ("import time\n"
              "def stamp(rec):\n"
              "    rec['ts'] = time.time()\n")
        assert "XGT006" not in codes(ok)

    def test_xgt007_collective_under_rank_branch(self):
        bad = ("def sync(rank, x):\n"
               "    if rank == 0:\n"
               "        x = allreduce(x)\n"
               "    return x\n")
        assert "XGT007" in codes(bad, path="xgboost_tpu/parallel/dp.py")
        # the mesh-fused driver made learner.py a distributed seam too
        assert "XGT007" in codes(bad, path="xgboost_tpu/learner.py")
        # scoped: same code outside the distributed seams is quiet
        assert "XGT007" not in codes(bad, path="xgboost_tpu/data.py")
        # every-rank collective with a rank branch around the DATA is
        # the documented fix
        ok = ("def sync(rank, x, y):\n"
              "    payload = x if rank == 0 else y\n"
              "    return allreduce(payload)\n")
        assert "XGT007" not in codes(ok, path="xgboost_tpu/parallel/dp.py")

    @pytest.mark.parametrize("code,snippet,path", [
        ("XGT001", "import jax\nfor x in xs:\n"
         "    y = jax.jit(lambda a: a)(x)  # xgtpu: disable=XGT001\n",
         "xgboost_tpu/foo.py"),
        ("XGT002", "def g(ns):\n    for n in ns:\n"
         "        v = n.item()  # xgtpu: disable=XGT002\n",
         "xgboost_tpu/ops/split.py"),
        ("XGT003", "f = open(p, 'w')  # xgtpu: disable=XGT003\n",
         "xgboost_tpu/foo.py"),
        ("XGT004", "try:\n    g()\n"
         "except Exception:  # xgtpu: disable=XGT004\n    pass\n",
         "xgboost_tpu/foo.py"),
        ("XGT006", "import time\nd = time.time() - t0"
         "  # xgtpu: disable=XGT006\n", "xgboost_tpu/foo.py"),
        ("XGT007", "if rank:\n"
         "    # xgtpu: disable=XGT007\n"
         "    x = allreduce(x)\n", "xgboost_tpu/parallel/dp.py"),
    ])
    def test_inline_suppression_silences(self, code, snippet, path):
        assert code not in codes(snippet, path=path)
        assert code in suppressed_codes(snippet, path=path)

    def test_file_wide_suppression(self):
        src = ("# xgtpu: disable-file=XGT004\n"
               "try:\n    g()\nexcept Exception:\n    pass\n")
        assert codes(src, path="xgboost_tpu/foo.py") == []

    def test_disable_all(self):
        src = ("import time\n"
               "d = time.time() - t0  # xgtpu: disable=all\n")
        assert codes(src, path="xgboost_tpu/foo.py") == []

    def test_syntax_error_is_a_finding_not_a_crash(self):
        active, _ = analyze_source("def broken(:\n", path="x.py")
        assert [f.rule for f in active] == ["XGT000"]

    def test_directive_in_string_or_docstring_does_not_suppress(self):
        """Only REAL comments carry directives: a docstring that merely
        QUOTES the suppression syntax (as the engine's own docs do)
        must not disable anything."""
        src = ('"""Docs: silence with `# xgtpu: disable-file=XGT004`."""\n'
               "s = '# xgtpu: disable=XGT006'\n"
               "import time\n"
               "try:\n    g()\nexcept Exception:\n    pass\n"
               "d = time.time() - t0\n")
        assert codes(src, path="xgboost_tpu/foo.py") == ["XGT004",
                                                         "XGT006"]


# ---------------------------------------------------------------- baseline
class TestBaseline:
    BAD = ("import time\n"
           "def f():\n"
           "    return time.time() - 0\n")

    def test_roundtrip_absorbs_findings(self, tmp_path):
        p = tmp_path / "mod.py"
        p.write_text(self.BAD)
        dirty = run([str(p)])
        assert len(dirty.findings) == 1 and not dirty.clean
        base = Baseline.from_findings(dirty.findings)
        bpath = str(tmp_path / "base.json")
        base.dump(bpath)
        clean = run([str(p)], baseline=Baseline.load(bpath))
        assert clean.clean and len(clean.baselined) == 1

    def test_baseline_survives_line_drift(self, tmp_path):
        p = tmp_path / "mod.py"
        p.write_text(self.BAD)
        base = Baseline.from_findings(run([str(p)]).findings)
        # unrelated lines added above: content-addressed keys still match
        p.write_text("import os\n\n\n" + self.BAD)
        assert run([str(p)], baseline=base).clean

    def test_keys_stable_across_relative_and_absolute_paths(self):
        """A baseline written from a repo-root-relative invocation must
        absorb the identical finding from an absolute-path invocation
        (tools/xgtpu_lint.py vs python -m xgboost_tpu.analysis)."""
        from xgboost_tpu.analysis.core import Finding

        def key(path):
            return Finding("XGT006", path, 3, 0, "m",
                           "    d = time.time() - t0").baseline_key

        rel = os.path.join("xgboost_tpu", "cli.py")
        assert key(rel) == key(os.path.join(PKG_DIR, "cli.py"))
        assert "xgboost_tpu/cli.py" in key(rel)

    def test_new_finding_not_absorbed(self, tmp_path):
        p = tmp_path / "mod.py"
        p.write_text(self.BAD)
        base = Baseline.from_findings(run([str(p)]).findings)
        p.write_text(self.BAD + "    d2 = time.time() - 1\n")
        result = run([str(p)], baseline=base)
        assert len(result.findings) == 1  # only the NEW one fails


# --------------------------------------------------------------------- CLI
class TestCli:
    def test_exit_codes_and_json(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("import time\nd = time.time() - t0\n")
        assert lint_main([str(bad), "--no-baseline", "--json"]) == 1
        report = json.loads(capsys.readouterr().out)
        assert report["findings"][0]["rule"] == "XGT006"
        assert report["counts"] == {"XGT006": 1}
        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        assert lint_main([str(good), "--no-baseline"]) == 0
        assert lint_main([str(tmp_path / "missing.py")]) == 2

    def test_write_baseline_workflow(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import time\nd = time.time() - t0\n")
        bpath = str(tmp_path / "b.json")
        assert lint_main([str(bad), "--baseline", bpath,
                          "--write-baseline"]) == 0
        assert lint_main([str(bad), "--baseline", bpath]) == 0
        assert lint_main([str(bad), "--no-baseline"]) == 1

    def test_write_baseline_refuses_rules_subset(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import time\nd = time.time() - t0\n")
        assert lint_main([str(bad), "--baseline",
                          str(tmp_path / "b.json"), "--rules", "XGT006",
                          "--write-baseline"]) == 2

    def test_write_baseline_subset_scan_keeps_other_debt(self, tmp_path,
                                                         monkeypatch):
        """A --write-baseline over ONE subdirectory must not erase the
        accepted debt recorded for the rest of the tree."""
        from xgboost_tpu.analysis import core
        monkeypatch.setattr(core, "default_baseline_path",
                            lambda: str(tmp_path / "BASE.json"))
        bpath = str(tmp_path / "BASE.json")
        bad = "import time\nd = time.time() - t0\n"
        for sub in ("a", "b"):
            (tmp_path / sub).mkdir()
            (tmp_path / sub / "mod.py").write_text(bad)
        # accept everything, then re-write scanning only a/
        assert lint_main([str(tmp_path), "--baseline", bpath,
                          "--write-baseline"]) == 0
        assert lint_main([str(tmp_path / "a"), "--baseline", bpath,
                          "--write-baseline"]) == 0
        # b/'s entry survived: the full scan is still clean
        assert lint_main([str(tmp_path), "--baseline", bpath]) == 0

    def test_rules_filter_and_listing(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("import time\nd = time.time() - t0\n")
        assert lint_main([str(bad), "--no-baseline",
                          "--rules", "XGT003"]) == 0
        assert lint_main(["--rules", "XGT999", str(bad)]) == 2
        assert lint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for c in ("XGT001", "XGT004", "XGT007"):
            assert c in out

    def test_tools_wrapper_exists(self):
        path = os.path.join(os.path.dirname(PKG_DIR), "tools",
                            "xgtpu_lint.py")
        assert os.path.exists(path)


# ------------------------------------------------------------- enforcement
def test_package_tree_is_clean():
    """THE tier-1 gate: zero unsuppressed, non-baselined findings over
    the whole package — the invariants PR1-PR3 established (no steady
    recompiles, atomic persistence, lock discipline, counted failures)
    are now machine-enforced for every future PR."""
    baseline = (Baseline.load(default_baseline_path())
                if os.path.exists(default_baseline_path()) else None)
    result = run([PKG_DIR], baseline=baseline)
    assert result.files_scanned > 50
    report = "\n".join(f.render() for f in result.findings)
    assert result.clean, (
        f"xgtpu-lint found {len(result.findings)} new violation(s) — fix "
        "them, suppress with a justified `# xgtpu: disable=`, or (for "
        "accepted legacy debt) regenerate ANALYSIS_BASELINE.json via "
        f"`tools/xgtpu_lint.py --write-baseline`:\n{report}")


# -------------------------------------------------------- dynamic checkers
class _Account:
    """Deliberately racy toy: balance is guarded in deposit() but
    mutated bare in sneak()."""

    def __init__(self):
        self._lock = threading.Lock()
        self.balance = 0

    def deposit(self, n):
        with self._lock:
            self.balance += n

    def sneak(self, n):
        self.balance += n  # the bug the checker must catch


class TestLockRaceChecker:
    def test_seeded_race_is_caught(self):
        checker = LockRaceChecker()
        acct = checker.instrument(_Account(), locks=("_lock",),
                                  guarded=("balance",))
        threads = [threading.Thread(target=acct.deposit, args=(1,))
                   for _ in range(4)]
        threads += [threading.Thread(target=acct.sneak, args=(1,))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        kinds = [v.kind for v in checker.violations]
        assert kinds == ["unguarded-write"]
        assert "balance" in checker.violations[0].detail
        assert acct.balance == 5  # instrumentation never alters behavior

    def test_disciplined_object_is_clean(self):
        checker = LockRaceChecker()
        acct = checker.instrument(_Account(), locks=("_lock",),
                                  guarded=("balance",))
        threads = [threading.Thread(target=acct.deposit, args=(1,))
                   for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        checker.assert_clean()
        assert acct.balance == 8

    def test_two_instances_do_not_alias_locks(self):
        """Holding instance A's lock must not satisfy instance B's
        guard — lock identities are per-instance, not per-class."""
        checker = LockRaceChecker()
        a = checker.instrument(_Account(), locks=("_lock",),
                               guarded=("balance",))
        b = checker.instrument(_Account(), locks=("_lock",),
                               guarded=("balance",))
        with a._lock:
            b.balance = 1  # wrong lock held: must be recorded
        assert [v.kind for v in checker.violations] == ["unguarded-write"]

    def test_lock_order_inversion_detected(self):
        checker = LockRaceChecker()
        a = checker.wrap_lock("A")
        b = checker.wrap_lock("B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        kinds = [v.kind for v in checker.violations]
        assert kinds == ["lock-order-inversion"]
        with pytest.raises(AssertionError, match="lock-order-inversion"):
            checker.assert_clean()

    def test_batcher_under_stress_is_disciplined(self, lock_race_checker):
        """The real MicroBatcher, instrumented, under concurrent
        submits: its documented locking contract (queued-rows and the
        closed flag mutate only under _lock) must hold in practice —
        the fixture's teardown asserts no violations."""
        import numpy as np
        from xgboost_tpu.serving.batcher import MicroBatcher
        mb = MicroBatcher(lambda X, output_margin=False: X[:, 0] * 2,
                          max_batch_rows=64, max_wait_ms=1.0)
        lock_race_checker.instrument(
            mb, locks=("_lock",), guarded=("_queued_rows", "_closed"))
        errs = []

        def hammer(seed):
            rng = np.random.RandomState(seed)
            for _ in range(20):
                X = rng.rand(rng.randint(1, 9), 3).astype(np.float32)
                try:
                    out = mb.submit(X, timeout=10.0)
                    if not np.array_equal(out, X[:, 0] * 2):
                        errs.append("wrong result")
                except Exception as e:
                    errs.append(repr(e))

        threads = [threading.Thread(target=hammer, args=(s,))
                   for s in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        mb.close()
        assert not errs


class TestRecompileGuard:
    def test_steady_state_passes_and_recompile_fails(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp
        guard = RecompileGuard()
        f = jax.jit(lambda x: x * 2 + 1)
        # operands built OUTSIDE the guarded region: even an eager
        # `x + 1` compiles an XLA program, and the guard (correctly)
        # counts it — steady state means NO compiles, not "only jit
        # cache hits"
        x = jnp.arange(8.0)
        x2 = x + 1.0       # same shape/dtype: f's executable is reused
        y = jnp.arange(16.0)
        f(x)  # warmup compile
        with guard.expect(0):
            for _ in range(5):
                f(x)
            f(x2)
        with pytest.raises(AssertionError, match="recompile_guard"):
            with guard.expect(0):
                f(y)  # new shape: must compile

"""Data-parallel training tests on an 8-virtual-device CPU mesh.

The analog of the reference's local multi-process harness
(``subtree/rabit/tracker/rabit_demo.py`` + ``multi-node/`` scripts,
SURVEY.md §4.2): same training code, collectives over a real mesh.

Key property: row-split distributed training produces EXACTLY the same
model as single-device training (histogram psum is a sum either way and
the argmax tie-break is deterministic) — stronger than the reference,
which only guarantees consistent distributed state.
"""

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.parallel.mesh import data_parallel_mesh, set_mesh


@pytest.fixture
def mesh8():
    m = data_parallel_mesh(8)
    set_mesh(m)
    yield m
    set_mesh(None)


def make_data(n=4096, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = ((X[:, 0] > 0.5) ^ (X[:, 1] > 0.3) |
         (X[:, 2] > 0.9)).astype(np.float32)
    return X, y


def test_dp_matches_single_device(mesh8):
    X, y = make_data()
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.5}

    d1 = xgb.DMatrix(X, label=y)
    bst_single = xgb.train(params, d1, 5, verbose_eval=False)
    p_single = bst_single.predict(d1)

    d2 = xgb.DMatrix(X, label=y)
    bst_dp = xgb.train({**params, "dsplit": "row"}, d2, 5, verbose_eval=False)
    p_dp = bst_dp.predict(d2)

    np.testing.assert_allclose(p_single, p_dp, rtol=2e-4, atol=2e-5)


def test_dp_padding_odd_rows(mesh8):
    X, y = make_data(n=4091)  # not divisible by 8
    d = xgb.DMatrix(X, label=y)
    res = {}
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "dsplit": "row"}, d, 3, evals=[(d, "train")],
                    evals_result=res, verbose_eval=False)
    assert bst.predict(d).shape == (4091,)
    assert res["train-error"][-1] < 0.3


def test_dp_multiclass(mesh8):
    rng = np.random.RandomState(1)
    X = rng.randn(2048, 6).astype(np.float32)
    y = np.argmax(X[:, :3] + 0.2 * rng.randn(2048, 3), axis=1).astype(
        np.float32)
    d = xgb.DMatrix(X, label=y)
    res = {}
    xgb.train({"objective": "multi:softmax", "num_class": 3, "max_depth": 4,
               "dsplit": "row"}, d, 5, evals=[(d, "train")],
              evals_result=res, verbose_eval=False)
    assert res["train-merror"][-1] < 0.2


def test_dp_multiclass_base_margin_odd_rows(mesh8):
    """K>1 + base_margin + dsplit=row with padding: the raveled (n*K,)
    margin must pad per-row, and the model must match single-device."""
    rng = np.random.RandomState(5)
    n = 2043  # not divisible by 8
    X = rng.randn(n, 6).astype(np.float32)
    y = np.argmax(X[:, :3] + 0.2 * rng.randn(n, 3), axis=1).astype(np.float32)
    margin = rng.randn(n, 3).astype(np.float32) * 0.1
    params = {"objective": "multi:softprob", "num_class": 3, "max_depth": 3,
              "eta": 0.5}

    d_dp = xgb.DMatrix(X, label=y)
    d_dp.set_base_margin(margin.ravel())
    bst_dp = xgb.train({**params, "dsplit": "row"}, d_dp, 3,
                       verbose_eval=False)
    p_dp = bst_dp.predict(d_dp)
    assert p_dp.shape == (n, 3)

    d1 = xgb.DMatrix(X, label=y)
    d1.set_base_margin(margin.ravel())
    p1 = xgb.train(params, d1, 3, verbose_eval=False).predict(d1)
    np.testing.assert_allclose(p1, p_dp, rtol=2e-4, atol=2e-5)


def test_dp_padding_margin_invariant(mesh8):
    """Cached margins of padding rows must stay at base across rounds
    (they feed get_gradient; garbage would leak via prune's node-0 value)."""
    X, y = make_data(n=4091)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 4,
                     "eta": 0.5, "gamma": 0.2, "dsplit": "row"}, d, 3,
                    verbose_eval=False)
    entry = bst._cache[id(d)]
    margin = np.asarray(entry.margin).reshape(-1)
    valid = np.asarray(entry.row_valid).reshape(-1)
    base = np.asarray(entry.base).reshape(-1)
    np.testing.assert_allclose(margin[~valid], base[~valid], atol=1e-6)


def test_dp_deterministic(mesh8):
    X, y = make_data(n=2048)
    params = {"objective": "binary:logistic", "max_depth": 4,
              "subsample": 0.8, "seed": 11, "dsplit": "row"}
    d1 = xgb.DMatrix(X, label=y)
    p1 = xgb.train(params, d1, 3, verbose_eval=False).predict(d1)
    d2 = xgb.DMatrix(X, label=y)
    p2 = xgb.train(params, d2, 3, verbose_eval=False).predict(d2)
    np.testing.assert_array_equal(p1, p2)


def test_graft_entry_dryrun():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


def test_graft_entry_forward():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    import jax
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert np.all(np.isfinite(np.asarray(out)))


def test_dp_custom_objective_odd_rows(mesh8):
    """Custom-objective (fobj) path under row sharding with padding:
    predictions seen by fobj must have exactly num_row entries and the
    padded gradient rows must not perturb the model."""
    X, y = make_data(n=4091)
    d = xgb.DMatrix(X, label=y)

    def logistic_obj(preds, dmat):
        labels = dmat.get_label()
        assert preds.shape == (4091,)
        grad = preds - labels
        hess = preds * (1.0 - preds)
        return grad, hess

    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.5,
              "dsplit": "row"}
    bst = xgb.train(params, d, 3, obj=logistic_obj, verbose_eval=False)
    bst_builtin = xgb.train(params, xgb.DMatrix(X, label=y), 3,
                            verbose_eval=False)
    np.testing.assert_allclose(bst.predict(d),
                               bst_builtin.predict(xgb.DMatrix(X, label=y)),
                               rtol=2e-4, atol=2e-5)


def test_dp_pred_leaf_truncates_padding(mesh8):
    X, y = make_data(n=4091)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "dsplit": "row"}, d, 2, verbose_eval=False)
    leaves = bst.predict(d, pred_leaf=True)
    assert leaves.shape[0] == 4091


# ---------------------------------------------------------------- distcol
def test_colsplit_matches_single_device():
    """dsplit=col (DistColMaker analog): feature-sharded growth must
    reproduce the single-device model exactly — the SplitEntry argmax
    reduce and psum position bitmap change nothing numerically."""
    from xgboost_tpu.parallel.colsplit import feature_parallel_mesh

    X, y = make_data(n=2048, f=10)
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.5}

    d1 = xgb.DMatrix(X, label=y)
    bst_single = xgb.train(params, d1, 4, verbose_eval=False)
    p_single = bst_single.predict(d1)

    d2 = xgb.DMatrix(X, label=y)
    bst_col = xgb.train({**params, "dsplit": "col"}, d2, 4,
                        verbose_eval=False)
    p_col = bst_col.predict(d2)
    np.testing.assert_allclose(p_single, p_col, rtol=2e-4, atol=2e-5)

    # identical tree structure, not just predictions (cut_index is only
    # meaningful on real split nodes; elsewhere it holds argmax noise)
    for t1, t2 in zip(bst_single.gbtree.trees, bst_col.gbtree.trees):
        f1, f2 = np.asarray(t1.feature), np.asarray(t2.feature)
        np.testing.assert_array_equal(f1, f2)
        split = f1 >= 0
        np.testing.assert_array_equal(np.asarray(t1.cut_index)[split],
                                      np.asarray(t2.cut_index)[split])


def test_colsplit_feature_count_not_divisible():
    """F=13 features over 8 shards exercises the feature-padding path."""
    X, y = make_data(n=1024, f=13, seed=3)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 0.5, "dsplit": "col"}, d, 3,
                    evals=[(d, "train")], verbose_eval=False)
    err = ((bst.predict(d) > 0.5) != (y > 0.5)).mean()
    assert err < 0.1


def test_colsplit_with_gamma_prune():
    X, y = make_data(n=1024, f=10, seed=4)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 4,
                     "eta": 0.5, "gamma": 0.3, "dsplit": "col"}, d, 2,
                    verbose_eval=False)
    d_s = xgb.DMatrix(X, label=y)
    bst_s = xgb.train({"objective": "binary:logistic", "max_depth": 4,
                       "eta": 0.5, "gamma": 0.3}, d_s, 2, verbose_eval=False)
    np.testing.assert_allclose(bst.predict(d), bst_s.predict(d_s),
                               rtol=2e-4, atol=2e-5)


def test_sharded_dmatrix_single_process_bitmatch(mesh8, tmp_path):
    """ShardedDMatrix (per-rank split loading) in single-process mode:
    degenerates to loading everything, and training bit-matches the
    replicated device-sketch path — covering the block-split math,
    make_array_from_process_local_data assembly, distributed metric
    partials and local-shard prediction without subprocesses (the real
    2-process case lives in test_launch.py)."""
    rng = np.random.RandomState(13)
    N = 1003  # not divisible by the 8-device mesh
    X = rng.rand(N, 5)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0.7).astype(int)
    path = tmp_path / "t.libsvm"
    with open(path, "w") as fh:
        for i in range(N):
            # sparse: drop feature 2 on odd rows (missing-value handling)
            cols = [j for j in range(5) if not (j == 2 and i % 2)]
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in cols)
            fh.write(f"{y[i]} {feats}\n")

    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.7,
              "max_bin": 32, "dsplit": "row"}
    dm_s = xgb.ShardedDMatrix(str(path))
    assert dm_s.num_row == N and dm_s.local_num_row == N
    res_s = {}
    bst_s = xgb.train(params, dm_s, 5, evals=[(dm_s, "train")],
                      evals_result=res_s, verbose_eval=False)

    dm_r = xgb.DMatrix(str(path))
    res_r = {}
    bst_r = xgb.train(dict(params, device_sketch=1), dm_r, 5,
                      evals=[(dm_r, "train")], evals_result=res_r,
                      verbose_eval=False)

    s_s, s_r = bst_s.gbtree.get_state(), bst_r.gbtree.get_state()
    for k in s_s:
        np.testing.assert_array_equal(s_s[k], s_r[k], err_msg=k)
    # distributed (partial-sum) metrics agree with the host metrics
    assert res_s["train-error"][-1] == pytest.approx(
        res_r["train-error"][-1], abs=1e-6)

    # local predictions cover exactly the local rows
    p = bst_s.predict(dm_s)
    assert p.shape == (dm_s.local_num_row,)
    assert float(np.mean((p > 0.5) != y)) < 0.05

    # auc partials reduce to the reference's mean-of-shards form
    res_auc = {}
    xgb.train(dict(params, eval_metric=["auc", "logloss"]),
              xgb.ShardedDMatrix(str(path)), 3,
              evals=[(dm_s, "train")], evals_result=res_auc,
              verbose_eval=False)
    assert 0.9 < res_auc["train-auc"][-1] <= 1.0
    assert res_auc["train-logloss"][-1] < 0.3

    # unsupported-in-sharded-mode surfaces are loud, not silent
    with pytest.raises(NotImplementedError):
        xgb.train(dict(params, objective="rank:pairwise"),
                  xgb.ShardedDMatrix(str(path)), 1, verbose_eval=False)
    with pytest.raises(NotImplementedError):
        dm_s.slice(np.arange(4))


def test_dp_gblinear_matches_single_device(mesh8):
    """Distributed gblinear (VERDICT r2 item 10): rows sharded over the
    mesh, Gf/Hf reductions psum'd — matches single-device coordinate
    descent to float tolerance, padding rows inert."""
    rng = np.random.RandomState(3)
    n = 1021  # not divisible by 8: exercises zero-padding rows
    X = rng.rand(n, 6).astype(np.float32)
    w_true = np.array([1.5, -2.0, 0.0, 0.7, 0.0, 0.3], np.float32)
    y = (X @ w_true + 0.1 * rng.randn(n) > 0.5).astype(np.float32)
    params = {"booster": "gblinear", "objective": "binary:logistic",
              "eta": 0.5, "lambda": 0.1, "alpha": 0.05}

    d1 = xgb.DMatrix(X, label=y)
    bst1 = xgb.train(params, d1, 8, verbose_eval=False)
    p1 = bst1.predict(d1)

    d2 = xgb.DMatrix(X, label=y)
    res = {}
    bst2 = xgb.train({**params, "dsplit": "row"}, d2, 8,
                     evals=[(d2, "train")], evals_result=res,
                     verbose_eval=False)
    p2 = bst2.predict(d2)

    assert p2.shape == (n,)
    np.testing.assert_allclose(p1, p2, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(bst1.gbtree.weight),
                               np.asarray(bst2.gbtree.weight),
                               rtol=2e-4, atol=2e-5)
    assert res["train-error"][-1] < 0.2


def test_hlo_collectives_parser_forms():
    """The payload parser must not double-count: operand names that
    contain the opcode ('%all-reduce.3'), async -start tuple results
    (operand-alias + produced buffer), and -done ops all tripped a
    looser regex (review round 4)."""
    from xgboost_tpu.parallel.commcost import hlo_collectives
    hlo = """
  %all-reduce.4 = f32[16,28,64,2] all-reduce(f32[16,28,64,2] %all-reduce.3), replica_groups={{}}
  %ar-start = (f32[32,28,64,2], f32[32,28,64,2]) all-reduce-start(f32[32,28,64,2] %p), to_apply=%add
  %ar-done = f32[32,28,64,2] all-reduce-done((f32[32,28,64,2], f32[32,28,64,2]) %ar-start)
  %ag = (f32[8,4], f32[16,4]) all-gather-start(f32[8,4] %x), dimensions={0}
  %cps = (f32[8,4], f32[8,4], u32[], u32[]) collective-permute-start(f32[8,4] %y), source_target_pairs={{0,1}}
  ROOT %t = (f32[4], f32[8]) all-reduce(f32[4] %a, f32[8] %b), to_apply=%add
"""
    out = hlo_collectives(hlo)
    assert [(op, b) for op, _, b in out] == [
        ("all-reduce", 16 * 28 * 64 * 2 * 4),    # operand NOT counted
        ("all-reduce", 32 * 28 * 64 * 2 * 4),    # -start: buffer only
        ("all-gather", 16 * 4 * 4),              # -start: produced buf
        ("collective-permute", 8 * 4 * 4),       # context u32[]s not
        ("all-reduce", 4 * 4 + 8 * 4),           # fused tuple: both
    ], out


def test_dp_collectives_in_compiled_program(mesh8):
    """Multi-chip claim strengthener (VERDICT r2 weak #7): lower the
    bench-shaped distributed training step over the 8-device mesh and
    assert the COMPILED program contains the expected collectives — the
    histogram psum (the reference's histred.Allreduce role) — and that
    an actual step executes with the bench depth/bins."""
    import jax
    import jax.numpy as jnp
    from xgboost_tpu.binning import bin_dense, compute_cuts
    from xgboost_tpu.config import TrainParam
    from xgboost_tpu.models.gbtree import make_grow_config
    from xgboost_tpu.models.tree import grow_tree
    from xgboost_tpu.parallel.dp import grow_tree_dp, shard_rows

    rng = np.random.RandomState(0)
    N, F = 80_000, 28  # bench feature count; rows scaled for CPU CI
    X = rng.rand(N, F).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    cuts = compute_cuts(xgb.DMatrix(X, label=y), max_bin=64)
    cfg = make_grow_config(TrainParam(max_depth=6, eta=0.1, max_bin=64),
                           cuts.max_bin)
    gh = np.stack([0.5 - y, np.full(N, 0.25)], 1).astype(np.float32)

    mesh = mesh8
    args = (jax.random.PRNGKey(0),
            shard_rows(mesh, jnp.asarray(bin_dense(X, cuts))),
            shard_rows(mesh, jnp.asarray(gh)),
            jnp.asarray(cuts.cut_values), jnp.asarray(cuts.n_cuts),
            shard_rows(mesh, jnp.ones(N, bool)))

    fn = jax.jit(lambda k, b, g, cv, nc, rv: grow_tree_dp(
        mesh, k, b, g, cv, nc, cfg, rv))
    compiled = fn.lower(*args).compile()
    hlo = compiled.as_text()
    n_allreduce = hlo.count("all-reduce")
    # one histogram psum per non-terminal level (depths 0..5); terminal
    # node stats DERIVE from the split's child sums (no collective)
    assert n_allreduce >= 6, n_allreduce
    # the deepest histogram psum (depth 5, 32 nodes x 28 features x
    # n_bin bins x 2) rides the wire — the reference's histred.Allreduce
    # payload shape (TStats x bins x features x nodes, SURVEY §5.8)
    B = cfg.n_bin
    assert f"f32[32,{F},{B},2]" in hlo, "deepest histogram psum missing"

    # collective PAYLOAD accounting (VERDICT r3 item 2): the bytes on
    # the wire per round must match the analytic model
    # (commcost.hist_psum_bytes = the reference's histred.Allreduce
    # payload role, updater_histmaker-inl.hpp:343-346) — a payload
    # regression (extra collectives, wider stats, un-derived terminal
    # node_stats) fails here
    from xgboost_tpu.parallel.commcost import (hist_psum_bytes,
                                               hlo_collectives)
    colls = hlo_collectives(hlo)
    model = hist_psum_bytes(cfg.max_depth, F, B)
    ar_bytes = sum(b for op, _, b in colls if op == "all-reduce")
    for d, expect in model.items():
        shape = f"f32[{1 << d},{F},{B},2]"
        level = [b for op, s, b in colls
                 if op == "all-reduce" and shape in s]
        assert level and level[0] == expect, (d, shape, level)
    total = sum(model.values())
    assert total <= ar_bytes <= int(total * 1.05), (
        f"all-reduce bytes {ar_bytes} vs model {total}: "
        f"unexpected collective payload\n{colls}")

    tree, row_leaf, deltas = fn(*args)
    assert np.asarray(tree.feature).shape[0] == 127
    assert np.isfinite(np.asarray(deltas)).all()

    # and the distributed step matches single-device growth exactly
    t1, _, _ = grow_tree(args[0], jnp.asarray(bin_dense(X, cuts)),
                         jnp.asarray(gh), args[3], args[4], cfg)
    for f in tree._fields:
        np.testing.assert_allclose(np.asarray(getattr(tree, f)),
                                   np.asarray(getattr(t1, f)),
                                   rtol=1e-5, atol=1e-6, err_msg=f)


def test_exact_colsplit_bit_matches_single_device():
    """dsplit=col + grow_colmaker: TRUE exact column split at any
    cardinality (round 5 — the DistColMaker analog,
    updater_distcol-inl.hpp:136-153 over colmaker's scan
    :362-414; previously capped at max_exact_bin=4096 quantized cuts
    with a warning).  Each shard runs the segment-sorted exact finder
    on its own raw columns; winners reduce by all-gather + argmax and
    rows route by owner-masked raw-value psum.  The grown model must
    BIT-match the single-device exact grower — dumps equal,
    predictions equal — on ~50k distinct values per feature, with and
    without missing values, and no cap warning may fire."""
    import contextlib
    import io

    rng = np.random.RandomState(5)
    N = 50_000
    for nan_frac in (0.0, 0.08):
        X = rng.randn(N, 5).astype(np.float32)  # ~N distinct per feature
        if nan_frac:
            X[rng.rand(N, 5) < nan_frac] = np.nan
        y = ((np.nan_to_num(X[:, 0]) > 0.3)
             ^ (np.nan_to_num(X[:, 1]) < -0.2)).astype(np.float32)

        def run(extra):
            d = xgb.DMatrix(X, label=y)
            p = dict({"objective": "binary:logistic", "max_depth": 4,
                      "eta": 0.5, "updater": "grow_colmaker,prune",
                      "silent": 1}, **extra)
            err = io.StringIO()
            with contextlib.redirect_stderr(err):
                bst = xgb.train(p, d, 2)
            return bst, d, err.getvalue()

        b1, d1, _ = run({})
        b2, d2, log2 = run({"dsplit": "col"})
        assert b2.gbtree.exact_raw
        assert "max_exact_bin" not in log2, log2  # no cap warning
        assert b1.get_dump() == b2.get_dump()
        np.testing.assert_array_equal(np.asarray(b1.predict(d1)),
                                      np.asarray(b2.predict(d2)))


def test_project_round_time_uses_measured_fit():
    """The multi-chip projection's compute terms come from the MEASURED
    row-sweep fit in ROUND_MODEL.json (tools/fit_round_model.py) when
    present — not from the historical assumed intercept (VERDICT r4
    Missing #2)."""
    from xgboost_tpu.parallel.commcost import (fitted_round_model,
                                               project_round_time)
    model = fitted_round_model()
    proj = project_round_time(rows=1_000_000, max_depth=6, n_feat=28,
                              n_bin=64, n_chips=8,
                              single_chip_round_s=0.0144,
                              single_chip_rows=1_000_000)
    if model is not None:
        assert proj["fitted"] is True
        assert proj["fixed_round_s"] == model["fixed_round_s"]
        assert proj["per_row_s"] == model["per_row_s"]
        # the fit must actually be a fit: points + tight residuals
        assert len(model["points"]) >= 3
        assert model["fit_max_rel_err"] < 0.05
    else:
        assert proj["fitted"] is False
        assert proj["fixed_round_s"] == 0.004      # documented fallback
    # explicit overrides always win
    p2 = project_round_time(rows=1_000_000, max_depth=6, n_feat=28,
                            n_bin=64, n_chips=8,
                            single_chip_round_s=0.0144,
                            single_chip_rows=1_000_000,
                            fixed_round_s=0.008, per_row_s=1e-8)
    assert p2["fixed_round_s"] == 0.008 and p2["per_row_s"] == 1e-8
    # compute = fixed + per_row * rows/chip, exactly
    assert abs(p2["compute_s"] - (0.008 + 1e-8 * 125_000)) < 1e-12

"""Serving subsystem tests (xgboost_tpu.serving; design in SERVING.md).

Acceptance criteria covered here:
(a) engine predictions bitwise-equal to ``Learner.predict`` for every
    shape bucket (and at bucket boundaries / beyond the top bucket);
(b) after warmup, serving 100 mixed-size requests triggers ZERO new
    compiles — asserted via the engine's own compile counter AND via
    ``jax.monitoring`` backend-compile events (the XLA-level truth);
(c) hot-reload swaps models without dropping or corrupting in-flight
    requests (every concurrent response bit-matches exactly one of the
    two models — never a mixture).
"""

import json
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.serving import (MicroBatcher, ModelRegistry, PredictEngine,
                                 QueueFull, power_of_two_buckets, run_server)

# one process-global compile-event collector: jax.monitoring has no
# unregister, so tests read deltas of this list instead
_COMPILE_EVENTS = []
jax.monitoring.register_event_duration_secs_listener(
    lambda *a, **k: _COMPILE_EVENTS.append(a[0])
    if "backend_compile" in a[0] else None)


def _n_compiles() -> int:
    return len(_COMPILE_EVENTS)


def _train(seed=0, rounds=5, **params):
    rng = np.random.RandomState(seed)
    X = rng.rand(300, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    p = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.4,
         "silent": 1, "seed": seed, **params}
    bst = xgb.train(p, xgb.DMatrix(X, label=y), rounds)
    return bst, X, y


@pytest.fixture(scope="module")
def model(tmp_path_factory):
    bst, X, y = _train()
    path = str(tmp_path_factory.mktemp("serving") / "model.bin")
    bst.save_model(path)
    return bst, X, y, path


# --------------------------------------------------------------- engine
def test_engine_bitwise_parity_all_buckets(model):
    bst, X, _, path = model
    eng = PredictEngine(path, min_bucket=8, max_bucket=64)
    assert eng.buckets == [8, 16, 32, 64]
    rng = np.random.RandomState(1)
    # every bucket size, both boundaries, plus 1 row and a non-boundary
    sizes = sorted({1, 5, 7, 8, 9, 15, 16, 31, 32, 63, 64})
    for n in sizes:
        Xq = rng.rand(n, 6).astype(np.float32)
        ref = bst.predict(xgb.DMatrix(Xq))
        got = eng.predict(Xq)
        assert got.dtype == ref.dtype and got.shape == ref.shape
        assert np.array_equal(got, ref), f"n={n} diverged"
        # margins too (output_margin skips the transform)
        refm = bst.predict(xgb.DMatrix(Xq), output_margin=True)
        assert np.array_equal(eng.predict(Xq, output_margin=True), refm)


def test_engine_chunks_beyond_top_bucket(model):
    bst, _, _, path = model
    eng = PredictEngine(path, min_bucket=8, max_bucket=32)
    rng = np.random.RandomState(2)
    Xq = rng.rand(101, 6).astype(np.float32)  # 32+32+32+5 chunks
    assert np.array_equal(eng.predict(Xq), bst.predict(xgb.DMatrix(Xq)))


def test_engine_multiclass_parity():
    rng = np.random.RandomState(3)
    X = rng.rand(120, 5).astype(np.float32)
    y = rng.randint(0, 3, 120).astype(np.float32)
    bst = xgb.train({"objective": "multi:softprob", "num_class": 3,
                     "max_depth": 3, "silent": 1}, xgb.DMatrix(X, label=y), 3)
    eng = PredictEngine(bst, min_bucket=8, max_bucket=32)
    Xq = rng.rand(11, 5).astype(np.float32)
    ref = bst.predict(xgb.DMatrix(Xq))
    got = eng.predict(Xq)
    assert got.shape == (11, 3)
    assert np.array_equal(got, ref)
    # empty batches keep the objective's output shape (softprob: (0, K))
    assert eng.predict(np.zeros((0, 5), np.float32)).shape == (0, 3)


def test_engine_empty_batch_shapes(model):
    bst, _, _, path = model
    eng = PredictEngine(path, min_bucket=8, max_bucket=32)
    empty = np.zeros((0, 6), np.float32)
    assert eng.predict(empty).shape == (0,)  # binary: squeezed like n>0
    assert eng.predict(empty, output_margin=True).shape == (0,)


def test_engine_missing_and_narrow_rows(model):
    """NaN features and fewer-columns-than-model inputs bin like the
    learner path (missing -> bin 0)."""
    bst, _, _, path = model
    eng = PredictEngine(path, min_bucket=8, max_bucket=32)
    rng = np.random.RandomState(4)
    Xq = rng.rand(10, 6).astype(np.float32)
    Xq[Xq < 0.2] = np.nan
    assert np.array_equal(eng.predict(Xq), bst.predict(xgb.DMatrix(Xq)))
    narrow = rng.rand(6, 4).astype(np.float32)  # model has 6 features
    assert np.array_equal(eng.predict(narrow),
                          bst.predict(xgb.DMatrix(narrow, num_col=6)))


def test_zero_recompiles_after_warmup(model):
    """Acceptance (b): 100 mixed-size requests after warmup compile
    NOTHING — engine counter and XLA backend-compile events both."""
    _, _, _, path = model
    eng = PredictEngine(path, min_bucket=8, max_bucket=64, warmup=True)
    assert eng.num_compiled == len(eng.buckets)
    rng = np.random.RandomState(5)
    sizes = rng.randint(1, 65, size=100)
    c0, e0 = eng.compile_count, _n_compiles()
    for n in sizes:
        eng.predict(rng.rand(n, 6).astype(np.float32))
    assert eng.compile_count - c0 == 0
    assert _n_compiles() - e0 == 0, "steady-state request recompiled"


def test_recompile_guard_reproduces_zero_steady_state(model,
                                                      recompile_guard):
    """The generalized checker (xgboost_tpu.analysis.runtime, surfaced
    as the conftest ``recompile_guard`` fixture) reproduces acceptance
    (b) without this module's bespoke listener plumbing — the form any
    future test should use to pin a compile budget."""
    _, _, _, path = model
    eng = PredictEngine(path, min_bucket=8, max_bucket=64, warmup=True)
    rng = np.random.RandomState(11)
    queries = [rng.rand(n, 6).astype(np.float32)
               for n in rng.randint(1, 65, size=50)]
    with recompile_guard.expect(0):
        for Xq in queries:
            eng.predict(Xq)


def test_warmup_does_not_pollute_row_counters(model):
    """Warmup rows are synthetic: rows_total/padded_rows_total must stay
    at zero (dashboards count caller-supplied rows), while
    compiles_total records the warmup's compiles."""
    from xgboost_tpu.profiling import ServingMetrics
    _, _, _, path = model
    m = ServingMetrics()
    eng = PredictEngine(path, min_bucket=8, max_bucket=32, metrics=m,
                        warmup=True)
    assert m.rows.value == 0
    assert m.padded_rows.value == 0
    assert m.compiles.value == len(eng.buckets)
    eng.predict(np.zeros((3, 6), np.float32))
    assert m.rows.value == 3
    assert m.padded_rows.value == 5  # padded up to the 8-row bucket


def test_engine_rejects_gblinear():
    rng = np.random.RandomState(6)
    X = rng.rand(60, 4).astype(np.float32)
    bst = xgb.train({"booster": "gblinear", "objective": "reg:linear",
                     "silent": 1}, xgb.DMatrix(X, label=X[:, 0]), 2)
    with pytest.raises(NotImplementedError):
        PredictEngine(bst)


def test_bucket_ladder():
    assert power_of_two_buckets(8, 64) == [8, 16, 32, 64]
    assert power_of_two_buckets(1, 1) == [1]
    # max_bucket is a HARD memory cap: never exceeded
    assert power_of_two_buckets(8, 100) == [8, 16, 32, 64]
    assert power_of_two_buckets(9, 10) == [10]  # no pow2 fits the range
    with pytest.raises(ValueError):
        power_of_two_buckets(16, 8)


# -------------------------------------------------------------- batcher
def test_batcher_coalesces_concurrent_requests():
    calls = []

    def predict_fn(X, output_margin=False):
        calls.append(X.shape[0])
        time.sleep(0.01)
        return X[:, 0].copy()

    b = MicroBatcher(predict_fn, max_batch_rows=100, max_wait_ms=50,
                     max_queue_rows=1000)
    try:
        results = [None] * 6
        barrier = threading.Barrier(6)

        def worker(i):
            barrier.wait()
            Xi = np.full((2, 3), float(i), np.float32)
            results[i] = b.submit(Xi)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # every caller got ITS OWN rows back, in order
        for i, r in enumerate(results):
            assert np.array_equal(r, np.full(2, float(i), np.float32))
        # 6 near-simultaneous requests coalesced into fewer device calls
        assert len(calls) < 6, f"no coalescing: {calls}"
        assert sum(calls) == 12
    finally:
        b.close()


def test_batcher_backpressure_queuefull():
    from xgboost_tpu.profiling import ServingMetrics
    release = threading.Event()
    metrics = ServingMetrics()

    def predict_fn(X, output_margin=False):
        release.wait(5.0)
        return np.zeros(X.shape[0], np.float32)

    b = MicroBatcher(predict_fn, max_batch_rows=4, max_wait_ms=1,
                     max_queue_rows=10, metrics=metrics)
    try:
        t = threading.Thread(target=lambda: b.submit(np.zeros((4, 2))))
        t.start()
        time.sleep(0.05)  # worker picked up the first batch and blocked
        t2 = threading.Thread(target=lambda: b.submit(np.zeros((8, 2))))
        t2.start()
        time.sleep(0.05)  # 8 rows queued
        with pytest.raises(QueueFull):
            b.submit(np.zeros((5, 2)))  # 8 + 5 > 10 -> reject, not buffer
        # "requests received" includes the rejected one (reject ratio =
        # rejected/requests must stay <= 1)
        assert metrics.requests.value == 3
        assert metrics.rejected.value == 1
        release.set()
        t.join(5.0)
        t2.join(5.0)
    finally:
        release.set()
        b.close()


def test_batcher_admits_oversized_request_when_idle():
    """A single request bigger than max_queue_rows must not 503 forever:
    it is admitted when nothing is queued (the engine chunks it)."""
    def predict_fn(X, output_margin=False):
        return X[:, 0].copy()

    b = MicroBatcher(predict_fn, max_batch_rows=8, max_wait_ms=1,
                     max_queue_rows=10)
    try:
        big = np.arange(50, dtype=np.float32).reshape(25, 2)
        assert np.array_equal(b.submit(big), big[:, 0])
    finally:
        b.close()


def test_batcher_error_propagates_to_all_callers():
    def predict_fn(X, output_margin=False):
        raise RuntimeError("boom")

    b = MicroBatcher(predict_fn, max_wait_ms=1)
    try:
        with pytest.raises(RuntimeError, match="boom"):
            b.submit(np.zeros((2, 2)))
    finally:
        b.close()


def test_batcher_tenant_weighted_round_robin():
    """A heavy tenant's queued burst no longer serves ahead of a light
    tenant that arrived later: dequeues interleave by smooth WRR
    (weight 2 vs 1 → exactly 2:1), and the per-tenant dequeue counter
    accounts every pop."""
    gate = threading.Event()
    order = []

    def predict_fn(X, output_margin=False):
        gate.wait(5.0)
        return X[:, 0].copy()

    # max_batch_rows == one request's rows: every dequeue is its own
    # batch, so the service order IS the dequeue order
    b = MicroBatcher(predict_fn, max_batch_rows=2, max_wait_ms=1,
                     max_queue_rows=1000)
    b.set_tenant_weight("heavy", 2.0)
    orig = b._next_request

    def spy():
        req = orig()
        order.append(req.tenant)
        return req

    b._next_request = spy
    try:
        def worker(tenant):
            b.submit(np.zeros((2, 3), np.float32), tenant=tenant,
                     timeout=10)

        warm = threading.Thread(target=worker, args=("warm",))
        warm.start()
        time.sleep(0.1)  # worker now blocked in the warm flush
        ts = [threading.Thread(target=worker, args=("heavy",))
              for _ in range(6)]
        for t in ts:
            t.start()
        time.sleep(0.1)  # the whole heavy burst queued first...
        tl = [threading.Thread(target=worker, args=("light",))
              for _ in range(3)]
        for t in tl:
            t.start()
        time.sleep(0.1)  # ...then the light one, all behind the gate
        gate.set()
        for t in [warm] + ts + tl:
            t.join()
    finally:
        b.close()
    assert order[0] == "warm"
    # smooth WRR at weights (2, 1): heavy, light, heavy, heavy, ...
    assert order[1:] == ["heavy", "light", "heavy", "heavy", "light",
                         "heavy", "heavy", "light", "heavy"]
    from xgboost_tpu.obs.metrics import tenant_dequeues
    rendered = tenant_dequeues().render()
    assert 'xgbtpu_batcher_tenant_dequeues_total{model="heavy"}' in rendered
    assert 'xgbtpu_batcher_tenant_dequeues_total{model="light"}' in rendered


# ------------------------------------------------------------- registry
def test_hot_reload_swap_and_rollback(model, tmp_path):
    bst_a, X, _, _ = model
    path = str(tmp_path / "m.bin")
    bst_a.save_model(path)
    reg = ModelRegistry(path, keep_versions=2, warmup=False, poll_sec=0,
                        min_bucket=8, max_bucket=32)
    Xq = X[:10]
    ref_a = bst_a.predict(xgb.DMatrix(Xq))
    assert reg.version == 1
    assert np.array_equal(reg.predict(Xq), ref_a)
    # byte-identical rewrite is NOT a reload
    bst_a.save_model(path)
    assert reg.check_reload() is False
    assert reg.version == 1
    # a different model IS
    bst_b, _, _ = _train(seed=9, rounds=7, max_depth=2)
    bst_b.save_model(path)
    ref_b = bst_b.predict(xgb.DMatrix(Xq))
    assert reg.check_reload() is True
    assert reg.version == 2
    assert np.array_equal(reg.predict(Xq), ref_b)
    # instant rollback to the still-warm previous engine
    assert reg.rollback() is True
    assert np.array_equal(reg.predict(Xq), ref_a)
    # the rollback sticks: the unchanged on-disk file does not re-load
    assert reg.check_reload() is False
    # rollback is reversible: the swapped-out engine went onto the ring,
    # so a second rollback toggles back to model B
    assert reg.rollback() is True
    assert np.array_equal(reg.predict(Xq), ref_b)
    # keep_versions=0 disables the ring entirely
    reg0 = ModelRegistry(path, keep_versions=0, warmup=False, poll_sec=0,
                         min_bucket=8, max_bucket=32)
    assert reg0.rollback() is False


def test_hot_reload_under_concurrent_requests(model, tmp_path):
    """Acceptance (c): requests racing a model swap each get a response
    bit-matching exactly ONE model — old or new, never a mixture, never
    an error."""
    bst_a, X, _, _ = model
    path = str(tmp_path / "m.bin")
    bst_a.save_model(path)
    reg = ModelRegistry(path, warmup=False, poll_sec=0,
                        min_bucket=8, max_bucket=32)
    batcher = MicroBatcher(reg.predict, max_batch_rows=64, max_wait_ms=1,
                           max_queue_rows=100_000)
    bst_b, _, _ = _train(seed=11, rounds=6, max_depth=2)
    Xq = X[:7]
    ref_a = bst_a.predict(xgb.DMatrix(Xq))
    ref_b = bst_b.predict(xgb.DMatrix(Xq))
    assert not np.array_equal(ref_a, ref_b)

    stop = threading.Event()
    outputs, errors = [], []

    def hammer():
        while not stop.is_set():
            try:
                outputs.append(batcher.submit(Xq, timeout=10.0))
            except BaseException as e:  # noqa: BLE001 — recorded, asserted
                errors.append(e)

    try:
        ts = [threading.Thread(target=hammer) for _ in range(4)]
        for t in ts:
            t.start()
        time.sleep(0.2)           # requests flowing on model A
        bst_b.save_model(path)
        assert reg.check_reload()  # load + warm + swap, mid-traffic
        # a submit AFTER the swap must see model B (batches resolve the
        # engine at flush time) — deterministic, no timing window
        post_swap = batcher.submit(Xq, timeout=30.0)
        stop.set()
        for t in ts:
            t.join(10.0)
    finally:
        stop.set()
        batcher.close()
    assert not errors, f"in-flight requests failed: {errors[:3]}"
    assert np.array_equal(post_swap, ref_b)
    assert post_swap.model_version == 2  # tagged with the model that RAN
    assert len(outputs) > 3
    n_a = sum(bool(np.array_equal(o, ref_a)) for o in outputs)
    n_b = sum(bool(np.array_equal(o, ref_b)) for o in outputs)
    assert n_a + n_b == len(outputs), "a response matched NEITHER model"
    assert n_a > 0, "no request was served by the old model"
    # every response's version tag names the model that produced it
    for o in outputs:
        expect = 1 if np.array_equal(o, ref_a) else 2
        assert o.model_version == expect


# ----------------------------------------------------------------- http
def test_http_roundtrip_ephemeral_port(model, tmp_path):
    bst, X, _, _ = model
    path = str(tmp_path / "m.bin")
    bst.save_model(path)
    srv = run_server(path, port=0, min_bucket=8, max_bucket=32,
                     max_wait_ms=1, poll_sec=0, warmup=False,
                     quiet=True, block=False)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        Xq = np.round(X[:5], 6)
        body = "\n".join(",".join(f"{v:.6f}" for v in row)
                         for row in Xq).encode()
        resp = json.load(urllib.request.urlopen(urllib.request.Request(
            base + "/predict", data=body, method="POST")))
        ref = bst.predict(xgb.DMatrix(Xq))
        assert resp["rows"] == 5 and resp["model_version"] == 1
        assert np.allclose(resp["predictions"], ref, atol=1e-6)
        # libsvm body, same rows -> same predictions
        lib = "\n".join(
            "1 " + " ".join(f"{j}:{v:.6f}" for j, v in enumerate(row))
            for row in Xq).encode()
        resp2 = json.load(urllib.request.urlopen(urllib.request.Request(
            base + "/predict?format=libsvm", data=lib, method="POST")))
        assert resp2["predictions"] == resp["predictions"]
        # output_margin passthrough
        respm = json.load(urllib.request.urlopen(urllib.request.Request(
            base + "/predict?output_margin=1", data=body, method="POST")))
        refm = bst.predict(xgb.DMatrix(Xq), output_margin=True)
        assert np.allclose(respm["predictions"], refm, atol=1e-6)
        # healthz + metrics
        h = json.load(urllib.request.urlopen(base + "/healthz"))
        assert h["status"] == "ok" and h["model_version"] == 1
        mtext = urllib.request.urlopen(base + "/metrics").read().decode()
        for metric in ("xgbtpu_serving_requests_total",
                       "xgbtpu_serving_batch_rows_bucket",
                       "xgbtpu_serving_padded_rows_total",
                       "xgbtpu_serving_queue_rows",
                       "xgbtpu_serving_latency_seconds_bucket",
                       "xgbtpu_serving_latency_p99_seconds",
                       "xgbtpu_serving_model_version"):
            assert metric in mtext, f"{metric} missing from /metrics"
        # bad request -> 400, unknown route -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/predict", data=b"", method="POST"))
        assert ei.value.code == 400
        # client-input error (too many columns) -> 400, not 500
        wide = ",".join(["0.5"] * 9).encode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/predict", data=wide, method="POST"))
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/nope")
        assert ei.value.code == 404
        # keep-alive hygiene: a POST with a body on a side route must
        # not desync the reused connection (body fully drained)
        import http.client
        conn = http.client.HTTPConnection("127.0.0.1", srv.port)
        conn.request("POST", "/-/reload", body=b'{"force": true}')
        r1 = conn.getresponse()
        r1.read()
        assert r1.status == 200
        conn.request("POST", "/predict", body=body)
        r2 = conn.getresponse()
        out = json.loads(r2.read())
        assert r2.status == 200 and out["rows"] == 5
        conn.close()
    finally:
        srv.shutdown()


# ----------------------------------------------------- satellite fixes
def test_predict_accepts_plain_ndarray(model):
    """Satellite: Learner.predict auto-wraps 2-D arrays (and jax arrays
    and nested lists) into a transient DMatrix."""
    bst, X, _, _ = model
    ref = bst.predict(xgb.DMatrix(X[:20]))
    assert np.array_equal(bst.predict(X[:20]), ref)
    import jax.numpy as jnp
    assert np.array_equal(bst.predict(jnp.asarray(X[:20])), ref)
    assert np.array_equal(bst.predict([list(map(float, r))
                                       for r in X[:20]]), ref)


def test_sklearn_predict_uses_autowrap():
    rng = np.random.RandomState(8)
    X = rng.rand(100, 4).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(int)
    clf = xgb.XGBClassifier(n_estimators=3, silent=True).fit(X, y)
    assert clf._predict_data(X) is X  # no DMatrix re-wrap on the hot path
    assert (clf.predict(X) == y).mean() > 0.9
    # a non-NaN missing marker still wraps explicitly
    clf2 = xgb.XGBClassifier(n_estimators=3, silent=True, missing=-999.0)
    clf2.fit(X, y)
    assert isinstance(clf2._predict_data(X), xgb.DMatrix)


def test_ntree_limit_clamps_not_raises(model):
    """Satellite: ntree_limit beyond the trained rounds clamps to the
    full ensemble (hot-reloaded smaller model vs stale request param)."""
    bst, X, _, _ = model
    full = bst.predict(xgb.DMatrix(X[:10]))
    over = bst.predict(xgb.DMatrix(X[:10]), ntree_limit=10_000)
    assert np.array_equal(over, full)
    # direct model-layer call too
    stack, group = bst.gbtree._stack(10_000)
    assert stack.feature.shape[0] == bst.gbtree.num_trees
    # negative clamps to "all trees" instead of producing an empty stack
    stack_neg, _ = bst.gbtree._stack(-3)
    assert stack_neg.feature.shape[0] == bst.gbtree.num_trees


def test_predict_incremental_empty_is_noop(model):
    bst, X, _, _ = model
    import jax.numpy as jnp
    margin = jnp.zeros((4, 1), jnp.float32)
    out = bst.gbtree.predict_incremental(jnp.zeros((4, 6), jnp.uint8),
                                         margin, [])
    assert out is margin


def test_cli_usage_lists_serve_params(capsys):
    from xgboost_tpu.cli import main as cli_main
    assert cli_main([]) == 0
    out = capsys.readouterr().out
    assert "serve" in out
    for name in ("serve_port", "serve_max_batch_rows", "serve_max_wait_ms",
                 "serve_poll_sec", "serve_keep_versions"):
        assert name in out, f"{name} missing from CLI usage"


def test_serving_main_parser_builds():
    from xgboost_tpu.serving.__main__ import _build_parser
    args = _build_parser().parse_args(
        ["--model", "m.bin", "--port", "0", "--max-wait-ms", "5"])
    assert args.model == "m.bin" and args.port == 0
    assert args.max_wait_ms == 5.0

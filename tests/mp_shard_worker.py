"""Worker for test_launch.py: PER-RANK SPLIT LOADING end to end.

Each process parses only its row block of the libsvm file
(ShardedDMatrix), assembles the global binned array from process-local
data, trains over the global mesh, and — in the same job — trains a
second Booster from a fully replicated load (DMatrix + device_sketch)
to prove the models are BYTE-IDENTICAL: split loading changes where
bytes live, not the math (reference property:
simple_dmatrix-inl.hpp:89-96).
Usage: mp_shard_worker.py <libsvm_path> <out_prefix>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from xgboost_tpu.parallel.launch import init_worker  # noqa: E402

assert init_worker(local_device_count=2)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    path, out_prefix = sys.argv[1], sys.argv[2]
    rank = jax.process_index()
    assert jax.device_count() == 4

    import xgboost_tpu as xgb

    params = {"objective": "binary:logistic", "max_depth": 3,
              "eta": 0.7, "max_bin": 32, "dsplit": "row"}

    dm_s = xgb.ShardedDMatrix(path)
    # the whole point: this process's host arrays cover only ~N/2 rows
    with open(f"{out_prefix}.rank{rank}.rows", "w") as f:
        f.write(f"{dm_s.local_num_row} {dm_s.num_row}\n")

    # fused (no-evals) split-loaded training
    bst_s = xgb.train(params, dm_s, 5, verbose_eval=False)
    bst_s.save_model(f"{out_prefix}.rank{rank}.model")

    # same job, replicated load over the same mesh: the ensemble state
    # must be byte-identical (save_raw differs only in the param header:
    # the replicated run spells device_sketch explicitly)
    bst_r = xgb.train(dict(params, device_sketch=1), xgb.DMatrix(path), 5,
                      verbose_eval=False)
    s_s, s_r = bst_s.gbtree.get_state(), bst_r.gbtree.get_state()
    bitmatch = int(all(np.array_equal(s_s[k], s_r[k]) for k in s_s))

    # per-round path with DISTRIBUTED metric evaluation (partial sums)
    res = {}
    bst_e = xgb.train(params, xgb.ShardedDMatrix(path), 5,
                      evals=[(dm_s, "train")], evals_result=res,
                      verbose_eval=False)
    err = float(res["train-error"][-1])
    s_e = bst_e.gbtree.get_state()
    bitmatch_e = int(all(np.array_equal(s_e[k], s_s[k]) for k in s_s))

    # local-shard prediction comes back with local row count
    preds = bst_s.predict(dm_s)
    assert preds.shape == (dm_s.local_num_row,), preds.shape

    # EXACT distributed AUC (dist_auc=exact, the default) must equal
    # the replicated-load AUC; the reference-compat approximation
    # (mean of per-shard AUCs, evaluation-inl.hpp:405-414) is kept
    # behind dist_auc=approx
    auc_params = dict(params, eval_metric="auc")
    r_exact, r_approx, r_repl = {}, {}, {}
    xgb.train(auc_params, xgb.ShardedDMatrix(path), 3,
              evals=[(dm_s, "train")], evals_result=r_exact,
              verbose_eval=False)
    xgb.train(dict(auc_params, dist_auc="approx"),
              xgb.ShardedDMatrix(path), 3, evals=[(dm_s, "train")],
              evals_result=r_approx, verbose_eval=False)
    xgb.train(dict(auc_params, device_sketch=1), xgb.DMatrix(path), 3,
              evals=[(xgb.DMatrix(path), "train")], evals_result=r_repl,
              verbose_eval=False)

    with open(f"{out_prefix}.rank{rank}.result", "w") as f:
        f.write(f"{bitmatch} {bitmatch_e} {err:.6f}\n")
    with open(f"{out_prefix}.rank{rank}.auc", "w") as f:
        f.write(f"{r_exact['train-auc'][-1]:.9f} "
                f"{r_approx['train-auc'][-1]:.9f} "
                f"{r_repl['train-auc'][-1]:.9f}\n")
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("done")


if __name__ == "__main__":
    main()

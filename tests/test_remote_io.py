"""Remote-URI ingestion (VERDICT r3 item 8; reference io.cpp:32-35
routes s3://, hdfs:// etc. to dmlc-core's filesystem layer).

The seam has three openers (io/dispatch._fetch_remote): the
XGBTPU_REMOTE_CAT command override, scheme CLI clients, and fsspec.
These tests exercise the override (a mocked "s3") and fsspec's
memory:// filesystem end to end — including training from the fetched
matrix and the #cache fast path."""

import os
import stat

import numpy as np
import pytest

import xgboost_tpu as xgb

AGARICUS = "/root/reference/demo/data/agaricus.txt.train"


def _head(path, n_lines=400):
    with open(path, "rb") as f:
        return b"".join(f.readline() for _ in range(n_lines))


def test_remote_cat_override_trains(tmp_path, monkeypatch):
    """s3:// URI through a mocked fetcher command — the full pipeline
    (fetch -> parse -> train) and the #cache skip on reload."""
    local = tmp_path / "train.svm"
    local.write_bytes(_head(AGARICUS))
    fetcher = tmp_path / "fake_s3_cat.sh"
    fetcher.write_text(
        "#!/bin/sh\n"
        f"exec cat {local}\n")
    fetcher.chmod(fetcher.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("XGBTPU_REMOTE_CAT", str(fetcher))

    cache = tmp_path / "c"
    d = xgb.DMatrix(f"s3://fake-bucket/train.svm#{cache}")
    ref = xgb.DMatrix(str(local))
    assert d.num_row == ref.num_row and d.num_col == ref.num_col

    bst = xgb.train({"objective": "binary:logistic", "max_depth": 2},
                    d, 1, verbose_eval=False)
    assert len(bst.predict(d)) == d.num_row

    # second load must come from the cache, not the fetcher
    monkeypatch.setenv("XGBTPU_REMOTE_CAT", "/nonexistent-fetcher")
    d2 = xgb.DMatrix(f"s3://fake-bucket/train.svm#{cache}")
    assert d2.num_row == d.num_row


def test_fsspec_memory_filesystem(tmp_path):
    """Any fsspec-registered protocol works without a CLI client."""
    fsspec = pytest.importorskip("fsspec")
    blob = _head(AGARICUS, 200)
    with fsspec.open("memory://bucket/part0.svm", "wb") as f:
        f.write(blob)
    d = xgb.DMatrix("memory://bucket/part0.svm")
    assert d.num_row == 200
    assert np.isfinite(np.asarray(d.info.label)).all()


def test_unknown_scheme_names_all_seams(monkeypatch):
    monkeypatch.delenv("XGBTPU_REMOTE_CAT", raising=False)
    with pytest.raises(ValueError, match="XGBTPU_REMOTE_CAT"):
        xgb.DMatrix("nosuchscheme://bucket/x.svm")

"""Parity tests: Pallas histogram/node-stat kernels vs the XLA scatter.

Gradients are dyadic rationals (multiples of 1/256, bounded), so f32
summation is exact in ANY order — bitwise equality between the MXU
matmul formulation and the scatter-add is required, not just allclose.
Runs in Pallas interpret mode on the CPU test platform; the same kernels
compile on TPU (exercised by bench.py / the driver's real-chip run).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from xgboost_tpu.ops.histogram import (build_level_histogram,  # noqa: E402
                                       node_stats, stats_from_histogram)
from xgboost_tpu.ops.pallas_hist import (  # noqa: E402
    build_level_histogram_pallas, node_stats_pallas)


def _case(N, F, B, M, seed=0, frac_inactive=0.2):
    rng = np.random.RandomState(seed)
    binned = rng.randint(0, B, (N, F)).astype(np.uint8)
    gh = (rng.randint(-512, 512, (N, 2)) / 256.0).astype(np.float32)
    pos = rng.randint(0, M, N).astype(np.int32)
    pos[rng.rand(N) < frac_inactive] = -1
    return jnp.asarray(binned), jnp.asarray(gh), jnp.asarray(pos)


@pytest.mark.parametrize("N,F,B,M", [
    (1000, 13, 32, 8),     # generic odd sizes
    (513, 7, 67, 64),      # non-aligned rows, bench-like bin count
    (100, 3, 8, 4),        # smaller than one row tile
    (1024, 5, 16, 1),      # root level (single node)
    (7, 1, 4, 2),          # tiny
])
def test_pallas_histogram_bitwise_parity(N, F, B, M):
    binned, gh, pos = _case(N, F, B, M)
    want = np.asarray(build_level_histogram(binned, gh, pos, M, B))
    got = np.asarray(build_level_histogram_pallas(
        binned, gh, pos, M, B, interpret=True))
    assert got.shape == (M, F, B, 2)
    np.testing.assert_array_equal(got, want)


def test_pallas_histogram_all_inactive():
    binned, gh, pos = _case(64, 2, 8, 4)
    pos = jnp.full_like(pos, -1)
    got = np.asarray(build_level_histogram_pallas(
        binned, gh, pos, 4, 8, interpret=True))
    np.testing.assert_array_equal(got, np.zeros_like(got))


def test_pallas_histogram_bf16_mode_runs():
    """bf16 mode materializes the matmul operands in bf16 (the MXU would
    truncate them anyway; halving one-hot VMEM traffic is a measured
    kernel win).  The one-hot is 0/1 (exact in bf16), so the result must
    bitwise equal the exact histogram of bf16-rounded gradients —
    accumulation stays f32 in both formulations."""
    binned, gh, pos = _case(2000, 6, 32, 8, seed=3)
    gh_b = gh.astype(jnp.bfloat16).astype(jnp.float32)
    want = np.asarray(build_level_histogram(binned, gh_b, pos, 8, 32))
    got = np.asarray(build_level_histogram_pallas(
        binned, gh, pos, 8, 32, precision="bf16", interpret=True))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("N,M", [(1000, 8), (513, 64), (100, 1), (8, 2)])
def test_pallas_node_stats_parity(N, M):
    _, gh, pos = _case(N, 1, 4, M, seed=5)
    want = np.asarray(node_stats(gh, pos, M))
    got = np.asarray(node_stats_pallas(gh, pos, M, interpret=True))
    assert got.shape == (M, 2)
    np.testing.assert_array_equal(got, want)


def test_stats_from_histogram_matches_node_stats():
    binned, gh, pos = _case(800, 4, 16, 8, seed=9)
    hist = build_level_histogram(binned, gh, pos, 8, 16)
    np.testing.assert_allclose(np.asarray(stats_from_histogram(hist)),
                               np.asarray(node_stats(gh, pos, 8)),
                               rtol=0, atol=1e-5)


@pytest.mark.parametrize("N,F,B,M", [
    (600, 34, 40, 64),    # f_tile < F with F not a multiple of 8
    (600, 34, 256, 512),  # deep level forces f_tile rounding to 8s
])
def test_pallas_histogram_odd_feature_tiling(N, F, B, M):
    """Block sublane dims must be multiples of 8 or the full feature dim
    (regression: F=34 with a budget tile of 30 failed Mosaic lowering)."""
    binned, gh, pos = _case(N, F, B, M, seed=11)
    want = np.asarray(build_level_histogram(binned, gh, pos, M, B))
    got = np.asarray(build_level_histogram_pallas(
        binned, gh, pos, M, B, interpret=True))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("T,N,F,B,M", [
    (3, 500, 5, 16, 8),
    (6, 257, 4, 67, 64),   # bench-like bins, node-tiled level
    (2, 64, 3, 8, 1),      # root level
])
def test_batched_histogram_parity(T, N, F, B, M):
    """Tree-batched kernel == stacked per-tree kernels, bitwise (fp32)."""
    from xgboost_tpu.ops.pallas_hist import (
        build_level_histogram_pallas_batched)
    rng = np.random.RandomState(11)
    binned = jnp.asarray(rng.randint(0, B, (N, F)).astype(np.uint8))
    gh = jnp.asarray((rng.randint(-512, 512, (T, N, 2)) / 256.0)
                     .astype(np.float32))
    pos = rng.randint(0, M, (T, N)).astype(np.int32)
    pos[rng.rand(T, N) < 0.2] = -1
    pos = jnp.asarray(pos)
    got = np.asarray(build_level_histogram_pallas_batched(
        binned, gh, pos, M, B, interpret=True))
    assert got.shape == (T, M, F, B, 2)
    for t in range(T):
        want = np.asarray(build_level_histogram_pallas(
            binned, gh[t], pos[t], M, B, interpret=True))
        np.testing.assert_array_equal(got[t], want)


def test_vmap_dispatches_to_batched_kernel(monkeypatch):
    """jax.vmap of build_level_histogram over (gh, pos) must hit the
    custom_vmap rule (tree-batched kernel) and match per-tree results.

    The CPU test platform defaults to the scatter impl, which vmap
    handles natively — force the pallas impl (interpret mode) so this
    actually executes the custom_vmap wrapper and its def_vmap rule.
    """
    monkeypatch.setenv("XGBTPU_HIST", "pallas")
    rng = np.random.RandomState(12)
    T, N, F, B, M = 4, 300, 6, 32, 8
    binned = jnp.asarray(rng.randint(0, B, (N, F)).astype(np.uint8))
    gh = jnp.asarray((rng.randint(-512, 512, (T, N, 2)) / 256.0)
                     .astype(np.float32))
    pos = jnp.asarray(rng.randint(0, M, (T, N)).astype(np.int32))
    got = np.asarray(jax.vmap(
        lambda g, p: build_level_histogram(binned, g, p, M, B))(gh, pos))
    for t in range(T):
        want = np.asarray(build_level_histogram(binned, gh[t], pos[t],
                                                M, B))
        np.testing.assert_array_equal(got[t], want)


def test_vmap_batched_binned_falls_back_to_map(monkeypatch):
    """The custom_vmap rule's batched-binned branch (per-shard bins, no
    one-hot sharing) must also produce per-example results."""
    monkeypatch.setenv("XGBTPU_HIST", "pallas")
    rng = np.random.RandomState(13)
    T, N, F, B, M = 3, 120, 4, 16, 4
    binned = jnp.asarray(rng.randint(0, B, (T, N, F)).astype(np.uint8))
    gh = jnp.asarray((rng.randint(-512, 512, (T, N, 2)) / 256.0)
                     .astype(np.float32))
    pos = jnp.asarray(rng.randint(0, M, (T, N)).astype(np.int32))
    got = np.asarray(jax.vmap(
        lambda b, g, p: build_level_histogram(b, g, p, M, B))(
            binned, gh, pos))
    for t in range(T):
        want = np.asarray(build_level_histogram(binned[t], gh[t], pos[t],
                                                M, B))
        np.testing.assert_array_equal(got[t], want)


def test_native_split_finder_matches_standard():
    """find_best_splits_native on the kernel-native (F, B, 2, M) layout
    must equal find_best_splits on (M, F, B, 2) EXACTLY (same candidate
    order, tie-breaks, gains and winner sums)."""
    from xgboost_tpu.ops.split import (SplitConfig, find_best_splits,
                                       find_best_splits_native)
    rng = np.random.RandomState(0)
    M, F, B = 16, 7, 12
    hist = jnp.asarray(rng.rand(M, F, B, 2).astype(np.float32))
    hist = hist.at[..., 1].set(hist[..., 1] * 3)
    nst = hist[:, 0, :, :].sum(axis=1)
    n_cuts = jnp.asarray(rng.randint(3, B - 2, F).astype(np.int32))
    fmask = jnp.asarray(rng.rand(F) > 0.2)
    for cfg in (SplitConfig(min_child_weight=0.5),
                SplitConfig(reg_alpha=0.1, default_direction=1),
                SplitConfig(max_delta_step=0.7)):
        a = find_best_splits(hist, nst, n_cuts, cfg, fmask)
        b = find_best_splits_native(hist.transpose(1, 2, 3, 0), nst,
                                    n_cuts, cfg, fmask)
        for f in a._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
                err_msg=f)


def test_native_grow_matches_scatter(monkeypatch):
    """grow_tree through the native-layout prep path (pallas fp32,
    interpret on CPU) grows the EXACT tree the scatter path grows."""
    import jax.random
    from xgboost_tpu.binning import bin_dense, compute_cuts
    from xgboost_tpu.config import TrainParam
    from xgboost_tpu.data import DMatrix
    from xgboost_tpu.models.gbtree import make_grow_config
    from xgboost_tpu.models.tree import grow_tree
    from xgboost_tpu.ops.pallas_hist import host_transpose_bins

    rng = np.random.RandomState(3)
    X = rng.rand(2048, 5).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0.8).astype(np.float32)
    cuts = compute_cuts(DMatrix(X, label=y), max_bin=16)
    cfg = make_grow_config(TrainParam(max_depth=4, eta=0.5), cuts.max_bin)
    binned = bin_dense(X, cuts)
    bt = host_transpose_bins(binned, cuts.max_bin)
    gh = np.stack([0.5 - y, np.full_like(y, 0.25)], axis=1)
    args = (jax.random.PRNGKey(7), jnp.asarray(binned), jnp.asarray(gh),
            jnp.asarray(cuts.cut_values), jnp.asarray(cuts.n_cuts), cfg)

    monkeypatch.setenv("XGBTPU_HIST", "pallas")
    t_n, rl_n, rv_n = jax.jit(
        lambda *a: grow_tree.__wrapped__(*a, binned_t=jnp.asarray(bt)),
        static_argnums=(5,))(*args)
    monkeypatch.setenv("XGBTPU_HIST", "scatter")
    t_s, rl_s, rv_s = jax.jit(
        lambda *a: grow_tree.__wrapped__(*a), static_argnums=(5,))(*args)
    for f in t_n._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(t_n, f)), np.asarray(getattr(t_s, f)),
            err_msg=f)
    np.testing.assert_array_equal(np.asarray(rl_n), np.asarray(rl_s))
    np.testing.assert_array_equal(np.asarray(rv_n), np.asarray(rv_s))


def test_native_vmapped_multiclass_matches_scatter(monkeypatch):
    """The ensemble (vmapped) native path — batched kernel emitting
    (T, F, B, 2, M) in one relayout — must train the same multiclass
    model as the scatter path (fp32 pallas, interpret on CPU)."""
    import xgboost_tpu as xgb

    rng = np.random.RandomState(5)
    X = rng.rand(3000, 5).astype(np.float32)
    yc = (X[:, 0] * 3).astype(np.int32) % 3
    params = {"objective": "multi:softmax", "num_class": 3,
              "max_depth": 3, "eta": 0.5, "max_bin": 16}

    preds = {}
    for impl in ("pallas", "scatter"):
        monkeypatch.setenv("XGBTPU_HIST", impl)
        d = xgb.DMatrix(X, label=yc)
        bst = xgb.Booster(params, cache=[d])
        bst.update(d, 0)
        bst.update(d, 1)
        preds[impl] = np.asarray(bst.predict(d, output_margin=True))
    np.testing.assert_allclose(preds["pallas"], preds["scatter"],
                               rtol=1e-5, atol=1e-6)

"""Quantile sketch tests — semantics of reference src/utils/quantile.h.

The reference's own in-code checker is WQSummary::CheckValid
(quantile.h:165-173); these tests enforce the same invariants plus the
rank-error guarantee eps * total_weight after merge/prune chains.
"""

import numpy as np
import pytest

from xgboost_tpu.sketch import (empty_summary, make_summary, merge_summaries,
                                propose_cuts, prune_summary, query_quantile,
                                sketch_column)


def exact_rank(values, weights, v):
    """(rmin, rmax) of value v in the exact weighted order."""
    below = weights[values < v].sum()
    at = weights[values == v].sum()
    return below, below + at


def test_make_summary_exact():
    rng = np.random.RandomState(0)
    vals = rng.randint(0, 50, size=1000).astype(np.float64)
    w = rng.rand(1000)
    s = make_summary(vals, w)
    s.check_valid()
    assert s.total_weight == pytest.approx(w.sum())
    for v in np.unique(vals)[::7]:
        rmin, rmax = exact_rank(vals, w, v)
        i = np.searchsorted(s.value, v)
        assert s.rmin[i] == pytest.approx(rmin)
        assert s.rmax[i] == pytest.approx(rmax)


def test_merge_matches_concatenation():
    rng = np.random.RandomState(1)
    a_vals, b_vals = rng.randn(300), rng.randn(400)
    a_w, b_w = rng.rand(300), rng.rand(400)
    merged = merge_summaries(make_summary(a_vals, a_w), make_summary(b_vals, b_w))
    direct = make_summary(np.concatenate([a_vals, b_vals]),
                          np.concatenate([a_w, b_w]))
    merged.check_valid()
    np.testing.assert_allclose(merged.value, direct.value)
    np.testing.assert_allclose(merged.rmin, direct.rmin, atol=1e-9)
    np.testing.assert_allclose(merged.rmax, direct.rmax, atol=1e-9)
    np.testing.assert_allclose(merged.wmin, direct.wmin, atol=1e-9)


def test_merge_with_duplicate_values_across_sides():
    a = make_summary(np.array([1.0, 2.0, 3.0]), np.array([1.0, 1.0, 1.0]))
    b = make_summary(np.array([2.0, 3.0, 4.0]), np.array([2.0, 2.0, 2.0]))
    m = merge_summaries(a, b)
    m.check_valid()
    direct = make_summary(np.array([1., 2., 3., 2., 3., 4.]),
                          np.array([1., 1., 1., 2., 2., 2.]))
    np.testing.assert_allclose(m.value, direct.value)
    np.testing.assert_allclose(m.rmax, direct.rmax)


def test_merge_empty():
    a = make_summary(np.arange(5.0), None)
    assert merge_summaries(a, empty_summary()) is a
    assert merge_summaries(empty_summary(), a) is a


def test_prune_bounds_rank_error():
    rng = np.random.RandomState(2)
    vals = rng.randn(20000)
    w = np.ones(20000)
    s = make_summary(vals, w)
    maxsize = 64
    p = prune_summary(s, maxsize)
    p.check_valid()
    assert p.size <= maxsize
    # rank error bound ~ 2 * total / maxsize (GK-style guarantee)
    assert p.max_error() <= 2.5 * s.total_weight / (maxsize - 2)
    # extremes preserved
    assert p.value[0] == s.value[0]
    assert p.value[-1] == s.value[-1]


def test_sketch_column_chunked_matches_eps():
    rng = np.random.RandomState(3)
    vals = rng.randn(50000)
    eps = 0.05
    s = sketch_column(vals, None, eps, chunk=7000)
    s.check_valid()
    assert s.size <= int(2.0 / eps)
    # query median within eps*N of the truth
    med = query_quantile(s, len(vals) / 2)
    true_rank = (vals < med).sum()
    assert abs(true_rank - len(vals) / 2) < 3 * eps * len(vals)


def test_propose_cuts_quantiles():
    vals = np.arange(10000, dtype=np.float64)
    s = make_summary(vals, None)
    cuts = propose_cuts(prune_summary(s, 300), 10)
    assert len(cuts) <= 9
    assert np.all(np.diff(cuts) > 0)
    # roughly even mass per bin
    bins = np.searchsorted(cuts, vals, side="right")
    counts = np.bincount(bins)
    assert counts.min() > 0.3 * len(vals) / (len(cuts) + 1)


def test_propose_cuts_few_distinct():
    # binary feature: every distinct value is a cut (the cut at the minimum
    # enables missing-vs-present splits on sparse indicator features)
    vals = np.array([0.0] * 50 + [1.0] * 50)
    cuts = propose_cuts(make_summary(vals, None), 256)
    assert list(cuts) == [0.0, 1.0]
    # split semantics at cut 1.0: v=0 goes left, v=1 goes right
    assert np.searchsorted(cuts, 0.0, side="right") <= 1
    assert np.searchsorted(cuts, 1.0, side="right") == 2

"""Multi-host launcher (parallel/launch.py) — the tracker/submitter
analog (reference ``tracker/rabit_demo.py`` + ``rabit_tracker.py``).

Spawns REAL separate processes that rendezvous through
``jax.distributed.initialize`` and run the dp growth path over a global
(cross-process) mesh; the grown tree must match an in-process run on
the same global mesh shape.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_grow_worker.py")


def _clean_env():
    env = dict(os.environ)
    # the pytest process forced an 8-device CPU platform; workers set up
    # their own 2-device platforms
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_two_process_growth_matches_in_process(tmp_path):
    out = tmp_path / "tree.npz"
    cmd = [sys.executable, "-m", "xgboost_tpu.launch", "-n", "2",
           "--local-devices", "2", "--",
           sys.executable, WORKER, str(out)]
    r = subprocess.run(cmd, cwd=REPO, env=_clean_env(),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    assert out.exists(), r.stderr[-3000:]
    got = dict(np.load(str(out)))

    # in-process reference: same data, same 4-device mesh shape
    import jax
    import jax.numpy as jnp
    from xgboost_tpu.binning import bin_dense, compute_cuts
    from xgboost_tpu.config import TrainParam
    from xgboost_tpu.data import DMatrix
    from xgboost_tpu.models.gbtree import make_grow_config
    from xgboost_tpu.parallel.dp import grow_tree_dp, shard_rows
    from xgboost_tpu.parallel.mesh import data_parallel_mesh

    rng = np.random.RandomState(0)
    X = rng.rand(1024, 6).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0.8).astype(np.float32)
    cuts = compute_cuts(DMatrix(X, label=y), max_bin=16)
    cfg = make_grow_config(TrainParam(max_depth=3, eta=0.5), cuts.max_bin)
    p = np.float32(0.5)
    gh = np.stack([p - y, np.full_like(y, p * (1 - p))], axis=1)

    mesh = data_parallel_mesh(4)
    tree, _, _ = grow_tree_dp(
        mesh, jax.random.PRNGKey(7), shard_rows(mesh, jnp.asarray(
            bin_dense(X, cuts))), shard_rows(mesh, jnp.asarray(gh)),
        jnp.asarray(cuts.cut_values), jnp.asarray(cuts.n_cuts), cfg,
        shard_rows(mesh, jnp.ones(1024, bool)))

    for f in tree._fields:
        np.testing.assert_allclose(
            got[f], np.asarray(getattr(tree, f)), rtol=1e-5, atol=1e-6,
            err_msg=f)


def test_launcher_keepalive_restarts(tmp_path):
    """A worker that dies nonzero on trial 0 is restarted with a bumped
    XGBTPU_NUM_TRIAL (the rabit_demo keepalive loop)."""
    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, sys\n"
        "if int(os.environ.get('XGBTPU_NUM_TRIAL', '0')) == 0:\n"
        "    sys.exit(3)\n"
        "print('survived trial', os.environ['XGBTPU_NUM_TRIAL'])\n")
    from xgboost_tpu.parallel.launch import launch_local
    rc = launch_local(1, [sys.executable, str(script)], keepalive=True)
    assert rc == 0

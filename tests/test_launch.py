"""Multi-host launcher (parallel/launch.py) — the tracker/submitter
analog (reference ``tracker/rabit_demo.py`` + ``rabit_tracker.py``).

Spawns REAL separate processes that rendezvous through
``jax.distributed.initialize`` and run the dp growth path over a global
(cross-process) mesh; the grown tree must match an in-process run on
the same global mesh shape.
"""

import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mp_grow_worker.py")


def _clean_env():
    env = dict(os.environ)
    # the pytest process forced an 8-device CPU platform; workers set up
    # their own 2-device platforms
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_two_process_growth_matches_in_process(tmp_path):
    out = tmp_path / "tree.npz"
    cmd = [sys.executable, "-m", "xgboost_tpu.launch", "-n", "2",
           "--local-devices", "2", "--",
           sys.executable, WORKER, str(out)]
    r = subprocess.run(cmd, cwd=REPO, env=_clean_env(),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    assert out.exists(), r.stderr[-3000:]
    got = dict(np.load(str(out)))

    # in-process reference: same data, same 4-device mesh shape
    import jax
    import jax.numpy as jnp
    from xgboost_tpu.binning import bin_dense, compute_cuts
    from xgboost_tpu.config import TrainParam
    from xgboost_tpu.data import DMatrix
    from xgboost_tpu.models.gbtree import make_grow_config
    from xgboost_tpu.parallel.dp import grow_tree_dp, shard_rows
    from xgboost_tpu.parallel.mesh import data_parallel_mesh

    rng = np.random.RandomState(0)
    X = rng.rand(1024, 6).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0.8).astype(np.float32)
    cuts = compute_cuts(DMatrix(X, label=y), max_bin=16)
    cfg = make_grow_config(TrainParam(max_depth=3, eta=0.5), cuts.max_bin)
    p = np.float32(0.5)
    gh = np.stack([p - y, np.full_like(y, p * (1 - p))], axis=1)

    mesh = data_parallel_mesh(4)
    tree, _, _ = grow_tree_dp(
        mesh, jax.random.PRNGKey(7), shard_rows(mesh, jnp.asarray(
            bin_dense(X, cuts))), shard_rows(mesh, jnp.asarray(gh)),
        jnp.asarray(cuts.cut_values), jnp.asarray(cuts.n_cuts), cfg,
        shard_rows(mesh, jnp.ones(1024, bool)))

    for f in tree._fields:
        np.testing.assert_allclose(
            got[f], np.asarray(getattr(tree, f)), rtol=1e-5, atol=1e-6,
            err_msg=f)


def test_four_process_growth_matches_in_process(tmp_path):
    """The same dp growth over FOUR processes x 2 devices (8-device
    global mesh): the launcher, rendezvous and collective layout must
    hold beyond the 2-process case (VERDICT r3 weak #6 — wider gang
    coverage), and the tree must match an in-process 8-device run."""
    out = tmp_path / "tree4.npz"
    cmd = [sys.executable, "-m", "xgboost_tpu.launch", "-n", "4",
           "--local-devices", "2", "--",
           sys.executable, WORKER, str(out)]
    r = subprocess.run(cmd, cwd=REPO, env=_clean_env(),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    assert out.exists(), r.stderr[-3000:]
    got = dict(np.load(str(out)))

    import jax
    import jax.numpy as jnp
    from xgboost_tpu.binning import bin_dense, compute_cuts
    from xgboost_tpu.config import TrainParam
    from xgboost_tpu.data import DMatrix
    from xgboost_tpu.models.gbtree import make_grow_config
    from xgboost_tpu.parallel.dp import grow_tree_dp, shard_rows
    from xgboost_tpu.parallel.mesh import data_parallel_mesh

    rng = np.random.RandomState(0)
    X = rng.rand(1024, 6).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0.8).astype(np.float32)
    cuts = compute_cuts(DMatrix(X, label=y), max_bin=16)
    cfg = make_grow_config(TrainParam(max_depth=3, eta=0.5), cuts.max_bin)
    p = np.float32(0.5)
    gh = np.stack([p - y, np.full_like(y, p * (1 - p))], axis=1)

    mesh = data_parallel_mesh(8)
    tree, _, _ = grow_tree_dp(
        mesh, jax.random.PRNGKey(7), shard_rows(mesh, jnp.asarray(
            bin_dense(X, cuts))), shard_rows(mesh, jnp.asarray(gh)),
        jnp.asarray(cuts.cut_values), jnp.asarray(cuts.n_cuts), cfg,
        shard_rows(mesh, jnp.ones(1024, bool)))

    for f in tree._fields:
        np.testing.assert_allclose(
            got[f], np.asarray(getattr(tree, f)), rtol=1e-5, atol=1e-6,
            err_msg=f)


def test_launcher_keepalive_restarts(tmp_path):
    """A worker that dies nonzero on trial 0 is restarted with a bumped
    XGBTPU_NUM_TRIAL (the rabit_demo keepalive loop)."""
    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, sys\n"
        "if int(os.environ.get('XGBTPU_NUM_TRIAL', '0')) == 0:\n"
        "    sys.exit(3)\n"
        "print('survived trial', os.environ['XGBTPU_NUM_TRIAL'])\n")
    from xgboost_tpu.parallel.launch import launch_local
    rc = launch_local(1, [sys.executable, str(script)], keepalive=True,
                      restart_backoff_sec=0.05)
    assert rc == 0


# a worker that heartbeats once, then wedges forever on trial 0 and
# exits clean on any later trial — the launcher-watchdog test double
# (mesh-free: no jax anywhere)
_STALL_SCRIPT = """\
import os, sys, time
trial = int(os.environ.get("XGBTPU_NUM_TRIAL", "0"))
hb = os.environ.get("XGBTPU_HEARTBEAT_DIR")
rank = os.environ.get("XGBTPU_WORKER_ID", "0")
if hb:
    with open(os.path.join(hb, f"hb-{rank}"), "w") as f:
        f.write("0")
if trial == 0:
    time.sleep(600)  # wedged: no further heartbeats
sys.exit(0)
"""


def test_watchdog_kills_and_restarts_stalled_gang(tmp_path, capfd):
    """ISSUE 10 tentpole (2): a gang that stops advancing (heartbeats
    stale for watchdog_stall_sec) is killed and restarted on a bumped
    trial — stall-detection keepalive, the allreduce_robust timeout
    analog.  Counter- and event-verified."""
    from xgboost_tpu.parallel.launch import launch_local
    from xgboost_tpu.profiling import reliability_metrics
    script = tmp_path / "staller.py"
    script.write_text(_STALL_SCRIPT)
    rm = reliability_metrics()
    base_stall = rm.launch_restarts.value("stall")
    rc = launch_local(1, [sys.executable, str(script)], keepalive=True,
                      watchdog_stall_sec=1.2, restart_backoff_sec=0.05,
                      standalone=True)
    assert rc == 0
    assert rm.launch_restarts.value("stall") == base_stall + 1
    err = capfd.readouterr().err
    assert "[launch] STALL" in err
    assert "restarting all 1 workers, trial 1 (reason stall" in err


def test_watchdog_no_keepalive_kills_and_returns_stall_rc(tmp_path):
    """Without keepalive the watchdog still UNWEDGES the job — the
    gang is killed and the distinct stall exit code surfaces."""
    from xgboost_tpu.parallel.launch import STALL_RC, launch_local
    script = tmp_path / "staller.py"
    script.write_text(_STALL_SCRIPT)
    rc = launch_local(1, [sys.executable, str(script)], keepalive=False,
                      watchdog_stall_sec=1.0, standalone=True)
    assert rc == STALL_RC


def test_stall_mock_watchdog_resume_bit_identical_composed_with_death(
        tmp_path):
    """Satellite: the `stall` mock kind composed with death, end to end
    through the REAL CLI (the local_recover.cc analog for hangs,
    mesh-free via --standalone): the worker wedges at (version 2,
    seqno 0, trial 0), the watchdog kills+restarts the gang, the
    restarted trial dies at (version 3, trial 1), keepalive restarts
    again, and the final model is BIT-identical to an uninterrupted
    run.  ``rounds_per_dispatch=2`` keeps two fused segments in the
    4-round job (mock replay no longer blocks fusion), so the stall
    lands mid-segment-1 (no checkpoint yet) and the death lands after
    the segment-boundary ring write — the second restart must resume
    from round 2, not from scratch."""
    data = tmp_path / "train.libsvm"
    rng = np.random.RandomState(5)
    X = rng.rand(300, 5)
    y = (X[:, 0] > 0.5).astype(int)
    with open(data, "w") as fh:
        for i in range(300):
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(5))
            fh.write(f"{y[i]} {feats}\n")
    common = [f"data={data}", "task=train", "num_round=4", "silent=2",
              "objective=binary:logistic", "max_depth=3", "eta=0.5",
              "max_bin=16", "rounds_per_dispatch=2"]
    ref = tmp_path / "ref.model"
    chaos = tmp_path / "chaos.model"
    env = _clean_env()
    r = subprocess.run(
        [sys.executable, "-m", "xgboost_tpu", *common,
         f"model_out={ref}", f"checkpoint_dir={tmp_path / 'ck_ref'}"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-3000:]
    r = subprocess.run(
        [sys.executable, "-m", "xgboost_tpu.launch", "-n", "1",
         "--standalone", "--keepalive", "--watchdog-stall-sec", "4",
         "--restart-backoff-sec", "0.2", "--",
         sys.executable, "-m", "xgboost_tpu", *common,
         f"model_out={chaos}", f"checkpoint_dir={tmp_path / 'ck'}",
         "mock=stall:2,0,0;die:3,0,1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    assert "[mock] stall at version=2" in r.stderr
    assert "[launch] STALL" in r.stderr
    assert "reason stall" in r.stderr
    assert "die at version=3" in r.stderr
    assert "reason death" in r.stderr
    assert "[ckpt] resume at round 2" in r.stderr

    import xgboost_tpu as xgb
    a = xgb.Booster(model_file=str(ref)).gbtree.get_state()
    b = xgb.Booster(model_file=str(chaos)).gbtree.get_state()
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


# a worker that runs the REAL gang protocol mesh-free (no jax): round
# boundaries call gang.on_round (fault firing, beacon observation,
# self-fencing) and gate the heartbeat exactly like mock.begin_round
_GANG_SCRIPT = """\
import os, sys, time
sys.path.insert(0, {repo!r})
from xgboost_tpu.parallel import gang
hb = os.environ.get("XGBTPU_HEARTBEAT_DIR")
rank = os.environ.get("XGBTPU_WORKER_ID", "0")
for v in range({rounds}):
    beat = gang.on_round(v)
    if hb and beat:
        with open(os.path.join(hb, f"hb-{{rank}}"), "w") as f:
            f.write(str(v))
    time.sleep({sleep})
gang.mark_done()
sys.exit(0)
"""


def _gang_worker(tmp_path, rounds, sleep=0.15):
    script = tmp_path / "gang_worker.py"
    script.write_text(
        _GANG_SCRIPT.format(repo=REPO, rounds=rounds, sleep=sleep))
    return [sys.executable, str(script)]


def test_plan_degrade_prefers_device_halving():
    """The re-plan ladder: halve local devices down the PR 12
    invariance ladder first, then shed workers one at a time, floored
    at min_workers; a minimal gang cannot degrade."""
    from xgboost_tpu.parallel.launch import plan_degrade
    assert plan_degrade(4, 4) == (4, 2)
    assert plan_degrade(4, 2) == (4, 1)
    assert plan_degrade(4, 1) == (3, 1)
    assert plan_degrade(2, None) == (1, None)
    assert plan_degrade(1, None) is None
    assert plan_degrade(1, 1) is None
    assert plan_degrade(2, None, min_workers=2) is None


def test_coordinator_state_roundtrip_and_corruption(tmp_path, capfd):
    """Coordinator snapshots carry the ring's CRC discipline: a clean
    roundtrip restores the plan + roster, and a flipped byte makes the
    snapshot unusable (fresh start), never a silently-wrong adoption."""
    from xgboost_tpu.parallel.launch import _read_state, _write_state
    p = str(tmp_path / "coord-state.json")
    st = {"full_n": 2, "cur_n": 1, "cur_devices": None, "degraded": True,
          "trial": 3, "hb_dir": None, "gang_dir": str(tmp_path),
          "workers": [{"rank": 0, "pid": 12345}]}
    _write_state(p, st, "pid777")
    got = _read_state(p)
    assert got == dict(st, holder="pid777")
    raw = bytearray(open(p, "rb").read())
    raw[10] ^= 0x20
    open(p, "wb").write(bytes(raw))
    capfd.readouterr()
    assert _read_state(p) is None
    assert "unusable" in capfd.readouterr().err


def test_host_loss_degrades_gang_and_grow_back_restores(
        tmp_path, monkeypatch, capfd):
    """ISSUE 17 tentpole (1) at the launcher seam: a permanent host
    loss (worker exits HOST_LOSS_RC + tombstone) immediately re-plans
    the gang one worker smaller; while degraded, a ``grow`` file in
    the gang dir re-expands to full size on the next restart."""
    import threading

    from xgboost_tpu.parallel.launch import launch_local
    from xgboost_tpu.profiling import reliability_metrics
    monkeypatch.setenv("XGBTPU_FAULTS", "host_loss@t0.r0.v1.")
    gang_dir = tmp_path / "gang"
    gang_dir.mkdir()
    rm = reliability_metrics()
    base = {k: rm.launch_restarts.value(k)
            for k in ("host_loss", "growback")}
    base_grow = rm.launch_growbacks.value

    stop = threading.Event()

    def grow_when_degraded():
        state = gang_dir / "coord-state.json"
        while not stop.is_set():
            try:
                if b'"degraded": true' in state.read_bytes():
                    (gang_dir / "grow").touch()
                    return
            except OSError:
                pass
            time.sleep(0.05)

    t = threading.Thread(target=grow_when_degraded, daemon=True)
    t.start()
    try:
        rc = launch_local(2, _gang_worker(tmp_path, rounds=12, sleep=0.2),
                          keepalive=True, standalone=True,
                          degrade_after=3, restart_backoff_sec=0.05,
                          gang_dir=str(gang_dir))
    finally:
        stop.set()
        t.join(timeout=5)
    assert rc == 0
    assert rm.launch_restarts.value("host_loss") == base["host_loss"] + 1
    assert rm.launch_restarts.value("growback") == base["growback"] + 1
    assert rm.launch_growbacks.value == base_grow + 1
    # the final attempt ran at FULL size, not degraded
    assert rm.launch_mesh_size.value == 2
    assert rm.launch_degraded.value == 0
    err = capfd.readouterr().err
    assert "[gang] HOST LOSS" in err
    assert "[launch] DEGRADE: re-planning 2x1 -> 1x1 (host loss" in err
    assert "[launch] GROW-BACK" in err


def test_partition_window_self_fence_and_restart(
        tmp_path, monkeypatch, capfd):
    """ISSUE 17 tentpole (3): a worker partitioned from the
    coordinator beacon past gang_partition_sec self-fences (exit
    FENCE_RC, no further writes) and keepalive restarts the gang —
    reason ``fence``, counter-verified on the launcher side."""
    from xgboost_tpu.parallel.launch import launch_local
    from xgboost_tpu.profiling import reliability_metrics
    monkeypatch.setenv("XGBTPU_FAULTS", "partition=30.0@t0.r0.v1.")
    rm = reliability_metrics()
    base = rm.launch_restarts.value("fence")
    rc = launch_local(1, _gang_worker(tmp_path, rounds=10, sleep=0.15),
                      keepalive=True, standalone=True,
                      gang_partition_sec=0.45,
                      restart_backoff_sec=0.05)
    assert rc == 0
    assert rm.launch_restarts.value("fence") == base + 1
    err = capfd.readouterr().err
    assert "[gang] partition window" in err
    assert "[gang] FENCED" in err
    assert "reason fence" in err


def test_coordinator_restart_adopts_live_gang(tmp_path, capfd):
    """ISSUE 17 tentpole (2): a coordinator restarted against a prior
    holder's state snapshot whose workers are all still alive ADOPTS
    them (no respawn), observes their clean exits via done markers,
    and removes the snapshot on success."""
    from xgboost_tpu.parallel.launch import _write_state, launch_local
    gang_dir = tmp_path / "gang"
    gang_dir.mkdir()
    state = str(gang_dir / "coord-state.json")
    sentinel = tmp_path / "respawned"
    # double-fork: the orphaned worker must NOT be our child, or its
    # exit leaves a zombie that os.kill(pid, 0) still sees as alive —
    # in the real failover it reparents to init and is reaped
    spawner = subprocess.run(
        [sys.executable, "-c",
         "import subprocess, sys\n"
         "p = subprocess.Popen([sys.executable, '-c', "
         "\"import os, sys, time; time.sleep(1.2); \"\n"
         "    \"open(os.path.join(sys.argv[1], 'done-0'), 'w')"
         ".write('done')\", sys.argv[1]])\n"
         "print(p.pid)\n",
         str(gang_dir)],
        capture_output=True, text=True, timeout=30)
    assert spawner.returncode == 0, spawner.stderr
    pid = int(spawner.stdout.strip())
    try:
        _write_state(state, {
            "full_n": 1, "cur_n": 1, "cur_devices": None,
            "degraded": False, "trial": 2, "hb_dir": None,
            "gang_dir": str(gang_dir),
            "workers": [{"rank": 0, "pid": pid}]}, "pid-dead")
        rc = launch_local(
            1, [sys.executable, "-c",
                f"open({str(sentinel)!r}, 'w').write('x')"],
            standalone=True, gang_dir=str(gang_dir), state_path=state)
    finally:
        try:
            os.kill(pid, 9)
        except OSError:
            pass
    assert rc == 0
    assert not sentinel.exists(), "adoption must not respawn the gang"
    assert not os.path.exists(state), "success removes the snapshot"
    assert "[launch] re-adopting live gang" in capfd.readouterr().err


def test_superseded_coordinator_exits_without_reaping(tmp_path, capfd):
    """The single-holder lease: a coordinator that sees the state-file
    holder change under it exits COORD_FENCED_RC WITHOUT touching the
    workers — they belong to the new holder now."""
    import threading

    from xgboost_tpu.parallel.launch import (COORD_FENCED_RC,
                                             _pid_alive, _read_state,
                                             _write_state, launch_local)
    gang_dir = tmp_path / "gang"
    gang_dir.mkdir()
    state = str(gang_dir / "coord-state.json")
    out = {}

    def run():
        out["rc"] = launch_local(
            1, [sys.executable, "-c", "import time; time.sleep(60)"],
            standalone=True, gang_dir=str(gang_dir), state_path=state)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    st = None
    while time.monotonic() < deadline:
        st = _read_state(state)
        if st and st.get("workers"):
            break
        time.sleep(0.05)
    assert st and st.get("workers"), "launcher never snapshotted"
    pid = int(st["workers"][0]["pid"])
    _write_state(state, st, "intruder")
    t.join(timeout=15)
    assert not t.is_alive()
    assert out["rc"] == COORD_FENCED_RC
    try:
        # the worker was NOT reaped: the new holder owns it
        assert _pid_alive(pid)
    finally:
        try:
            os.kill(pid, 9)
        except OSError:
            pass
    assert "[launch] coordinator fenced" in capfd.readouterr().err


def test_stale_lease_wait_blocks_until_renewals_stop(tmp_path):
    """Standby-side half of the lease: _wait_for_stale_lease must NOT
    return while the primary keeps bumping the state-file mtime, and
    must return shortly after the bumps stop."""
    import threading

    from xgboost_tpu.parallel.launch import _wait_for_stale_lease
    state = tmp_path / "coord-state.json"
    state.write_text("{}")

    def renew():
        for _ in range(8):
            os.utime(state, None)
            time.sleep(0.15)

    t = threading.Thread(target=renew, daemon=True)
    t0 = time.monotonic()
    t.start()
    _wait_for_stale_lease(str(state), 0.6, poll=0.05)
    elapsed = time.monotonic() - t0
    t.join()
    assert elapsed > 1.2, "took over while the primary was renewing"
    assert elapsed < 8.0


def test_two_process_full_booster_training(tmp_path):
    """FULL Booster training across 2 processes x 2 devices: both ranks
    must produce byte-identical models with good training error, and the
    model must be loadable for local prediction."""
    data = tmp_path / "train.libsvm"
    rng = np.random.RandomState(4)
    X = rng.rand(800, 6)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0.7).astype(int)
    with open(data, "w") as fh:
        for i in range(800):
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(6))
            fh.write(f"{y[i]} {feats}\n")

    out = tmp_path / "mp"
    cmd = [sys.executable, "-m", "xgboost_tpu.launch", "-n", "2",
           "--local-devices", "2", "--",
           sys.executable, os.path.join(REPO, "tests", "mp_train_worker.py"),
           str(data), str(out)]
    r = subprocess.run(cmd, cwd=REPO, env=_clean_env(),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]

    m0 = (tmp_path / "mp.rank0.model").read_bytes()
    m1 = (tmp_path / "mp.rank1.model").read_bytes()
    assert m0 == m1, "ranks diverged"
    err = float((tmp_path / "mp.rank0.err").read_text())
    assert err < 0.05, err

    import xgboost_tpu as xgb

    # the fused (no-evals) multi-process run produced the same model
    b_seq = xgb.Booster(model_file=str(tmp_path / "mp.rank0.model"))
    b_fus = xgb.Booster(
        model_file=str(tmp_path / "mp.rank0.fused.model"))
    s1, s2 = b_seq.gbtree.get_state(), b_fus.gbtree.get_state()
    for k in s1:
        np.testing.assert_array_equal(s1[k], s2[k], err_msg=k)
    mf0 = (tmp_path / "mp.rank0.fused.model").read_bytes()
    mf1 = (tmp_path / "mp.rank1.fused.model").read_bytes()
    assert mf0 == mf1, "fused ranks diverged"

    # the multi-process model predicts locally like any other model
    bst = xgb.Booster(model_file=str(tmp_path / "mp.rank0.model"))
    p = np.asarray(bst.predict(xgb.DMatrix(str(data))))
    assert float(np.mean((p > 0.5) != y)) < 0.05


def test_two_process_split_loading_bitmatches_replicated(tmp_path):
    """VERDICT r2 #1: per-rank split loading end to end.  Each process
    parses ONLY its row block (~N/2 host rows), assembles global device
    arrays from process-local data, and the resulting model is
    BYTE-IDENTICAL to a replicated-load run in the same job — both for
    the fused scan and the per-round path with distributed (partial-sum)
    metric evaluation."""
    data = tmp_path / "train.libsvm"
    rng = np.random.RandomState(11)
    N = 801  # deliberately not divisible by the 4-device mesh
    X = rng.rand(N, 6)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0.7).astype(int)
    with open(data, "w") as fh:
        for i in range(N):
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(6))
            fh.write(f"{y[i]} {feats}\n")

    out = tmp_path / "sh"
    cmd = [sys.executable, "-m", "xgboost_tpu.launch", "-n", "2",
           "--local-devices", "2", "--",
           sys.executable, os.path.join(REPO, "tests", "mp_shard_worker.py"),
           str(data), str(out)]
    r = subprocess.run(cmd, cwd=REPO, env=_clean_env(),
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]

    # each process held only ~N/2 host rows (the scaling property)
    tot = 0
    for rank in range(2):
        loc, glob = map(int, (tmp_path / f"sh.rank{rank}.rows"
                              ).read_text().split())
        assert glob == N
        assert loc <= -(-N // 4) * 2, (rank, loc)  # <= 2 device shards
        tot += loc
    assert tot == N

    for rank in range(2):
        bitmatch, bitmatch_e, err = (
            tmp_path / f"sh.rank{rank}.result").read_text().split()
        assert bitmatch == "1", "split-loaded model != replicated model"
        assert bitmatch_e == "1", "per-round model != fused model"
        assert float(err) < 0.05, err

    # exact distributed AUC (VERDICT r3 item 6): the sharded
    # allgather-runs value equals the replicated value to f64
    # summation order; the reference-compat approximation is close on
    # iid shards but not required (or expected) to match exactly
    for rank in range(2):
        exact, approx, repl = map(float, (
            tmp_path / f"sh.rank{rank}.auc").read_text().split())
        assert abs(exact - repl) < 1e-6, (exact, repl)
        assert abs(approx - repl) < 0.05, (approx, repl)

    # ranks agree and the model is locally usable
    m0 = (tmp_path / "sh.rank0.model").read_bytes()
    m1 = (tmp_path / "sh.rank1.model").read_bytes()
    assert m0 == m1
    import xgboost_tpu as xgb
    bst = xgb.Booster(model_file=str(tmp_path / "sh.rank0.model"))
    p = np.asarray(bst.predict(xgb.DMatrix(str(data))))
    assert float(np.mean((p > 0.5) != y)) < 0.05


def test_two_process_rank_specific_death_gang_restart(tmp_path):
    """mock=rank,version,seqno,ntrial under the launcher: only the named
    rank dies, the launcher restarts the whole gang (single processes
    cannot rejoin a live jax.distributed job), and training resumes from
    the checkpoint to a saved model."""
    data = tmp_path / "train.libsvm"
    rng = np.random.RandomState(9)
    X = rng.rand(400, 5)
    y = (X[:, 0] > 0.5).astype(int)
    with open(data, "w") as fh:
        for i in range(400):
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(5))
            fh.write(f"{y[i]} {feats}\n")

    model = tmp_path / "ft.model"
    cmd = [sys.executable, "-m", "xgboost_tpu.launch", "-n", "2",
           "--local-devices", "2", "--keepalive", "--",
           sys.executable, "-m", "xgboost_tpu",
           f"data={data}", "objective=binary:logistic", "max_depth=3",
           "eta=1.0", "num_round=4", "silent=2", "mock=1,2,0,0",
           f"checkpoint_dir={tmp_path / 'ck'}", f"model_out={model}"]
    # two full gang attempts (each pays jit compiles) plus the
    # coordination-service error propagation make this the slowest test
    r = subprocess.run(cmd, cwd=REPO, env=_clean_env(),
                       capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, r.stderr[-3000:]
    # the death fired on rank 1 only, and the gang restarted once
    assert "die at version=2" in r.stderr
    assert "restarting all 2 workers, trial 1" in r.stderr
    assert model.exists()
    # recovery-cost instrumentation (RECOVERY.md): the launcher
    # reports attempt/reap timing and the restarted rank 0 reports the
    # time to its checkpoint-resume point.  The dying worker must exit
    # HARD: normal interpreter teardown hangs ~minutes in the
    # jax.distributed client, which this wall-clock bound catches.
    m = re.search(r"attempt ran ([0-9.]+)s, reap ([0-9.]+)s", r.stderr)
    assert m, r.stderr[-2000:]
    assert float(m.group(2)) < 30.0, "reap took too long"
    m2 = re.search(r"\[ckpt\] resume at round 2 \(([0-9.]+)s", r.stderr)
    assert m2, r.stderr[-2000:]
    assert float(m2.group(1)) < 60.0, "resume point took too long"

    import xgboost_tpu as xgb
    bst = xgb.Booster(model_file=str(model))
    p = np.asarray(bst.predict(xgb.DMatrix(str(data))))
    assert float(np.mean((p > 0.5) != y)) < 0.05

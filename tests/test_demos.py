"""Demo suite as integration tests (VERDICT r3 item 9).

``demo/`` mirrors the reference's demo tree (reference
``demo/binary_classification/runexp.sh``, ``demo/guide-python/runall.sh``)
but nothing executed it in CI until now — a regression in any
walkthrough script or in the CLI surface the demos drive would have
been invisible.  These run the real shell entry points in
subprocesses (CPU, tiny round counts are already what the demos use).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "demo")


def _run(script, timeout=900):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the demos' shebang shells pick `python` from PATH; force this
    # interpreter so the venv running pytest is the one running demos
    env["PATH"] = (os.path.dirname(sys.executable) + os.pathsep
                   + env.get("PATH", ""))
    r = subprocess.run(["/bin/sh", script], capture_output=True,
                       text=True, timeout=timeout, env=env)
    assert r.returncode == 0, (
        f"{script} failed (rc={r.returncode})\n"
        f"--- stdout ---\n{r.stdout[-4000:]}\n"
        f"--- stderr ---\n{r.stderr[-4000:]}")
    return r.stdout


def test_binary_classification_runexp():
    out = _run(os.path.join(DEMO, "binary_classification", "runexp.sh"))
    assert "runexp ok" in out


@pytest.mark.slow
def test_guide_python_runall():
    out = _run(os.path.join(DEMO, "guide-python", "runall.sh"),
               timeout=1800)
    assert "== sklearn_examples ==" in out

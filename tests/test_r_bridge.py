"""Static consistency of the R package's Python bridge.

No R runtime exists in this image (COVERAGE.md row 5), so the
reticulate frontend cannot EXECUTE here; what CAN be verified is that
every Python attribute/method the R sources call through the bridge
actually exists with a compatible surface — the failure mode that
silently breaks reticulate frontends when the core API drifts."""

import os
import re

import xgboost_tpu as xgb

R_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "R-package", "R")


def _r_sources():
    out = []
    for name in sorted(os.listdir(R_DIR)):
        if name.endswith(".R"):
            with open(os.path.join(R_DIR, name)) as f:
                out.append((name, f.read()))
    assert out, "R sources missing"
    return out


def test_core_module_attributes_exist():
    refs = set()
    for _, src in _r_sources():
        refs.update(re.findall(r"\bcore\$([A-Za-z_][A-Za-z_.]*)", src))
    assert refs, "no core$ references found"
    for attr in refs:
        # dotted chains (core$foo.bar) resolve their root attribute
        assert hasattr(xgb, attr.split(".")[0]), f"core${attr} missing"


def test_booster_and_dmatrix_methods_exist():
    import numpy as np
    X = np.random.RandomState(0).rand(50, 3).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 2},
                    d, 1, verbose_eval=False)
    methods = set()
    for _, src in _r_sources():
        methods.update(re.findall(
            r"\b(?:bst|handle|dmat)\$([A-Za-z_]+)\(", src))
    assert methods
    for m in methods:
        assert hasattr(bst, m) or hasattr(d, m), f"bridge method {m} missing"

"""sklearn-API tests (reference demo/guide-python/sklearn_examples.py)."""

import numpy as np
import pytest

from xgboost_tpu import XGBClassifier, XGBRegressor
from xgboost_tpu.sklearn import SKLEARN_INSTALLED


def _reg_data(n=600, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = 3.0 * X[:, 0] - 2.0 * X[:, 1] ** 2 + 0.1 * rng.randn(n)
    return X, y.astype(np.float32)


def test_regressor_fit_predict():
    X, y = _reg_data()
    model = XGBRegressor(n_estimators=20, max_depth=4, learning_rate=0.3)
    model.fit(X[:500], y[:500])
    pred = model.predict(X[500:])
    mse = float(np.mean((pred - y[500:]) ** 2))
    assert mse < 0.1
    imp = model.feature_importances_
    assert imp.shape == (8,) and abs(imp.sum() - 1.0) < 1e-5
    assert imp[0] > 0.05  # the linear driver feature gets splits


def test_binary_classifier_with_string_labels():
    rng = np.random.RandomState(1)
    X = rng.rand(500, 5).astype(np.float32)
    y_raw = np.where(X[:, 0] + X[:, 1] > 1.0, "pos", "neg")
    model = XGBClassifier(n_estimators=15, max_depth=3)
    model.fit(X, y_raw)
    pred = model.predict(X)
    assert set(pred) <= {"pos", "neg"}
    assert (pred == y_raw).mean() > 0.93
    proba = model.predict_proba(X)
    assert proba.shape == (500, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)


def test_multiclass_classifier_auto_switch():
    rng = np.random.RandomState(2)
    X = rng.rand(600, 6).astype(np.float32)
    y = (X[:, 0] * 3).astype(int)  # 3 classes 0,1,2
    model = XGBClassifier(n_estimators=10, max_depth=3)
    model.fit(X, y)
    # per-fit switch: the booster trains softprob, the estimator's own
    # objective param is untouched (so clone()/refit stay clean)
    assert model.get_booster().param.objective == "multi:softprob"
    assert model.objective == "binary:logistic"
    assert model.n_classes_ == 3
    proba = model.predict_proba(X)
    assert proba.shape == (600, 3)
    assert (model.predict(X) == y).mean() > 0.9


def test_early_stopping_and_eval_set():
    X, y = _reg_data()
    model = XGBRegressor(n_estimators=200, max_depth=3, learning_rate=0.3)
    model.fit(X[:400], y[:400], eval_set=[(X[400:], y[400:])],
              early_stopping_rounds=5)
    assert hasattr(model, "best_iteration_")
    assert model.best_iteration_ < 199
    assert "validation_0-rmse" in model.evals_result_


def test_get_set_params_roundtrip():
    model = XGBRegressor(n_estimators=7, subsample=0.8)
    p = model.get_params()
    assert p["n_estimators"] == 7 and p["subsample"] == 0.8
    model.set_params(max_depth=5)
    assert model.max_depth == 5
    with pytest.raises(ValueError):
        model.set_params(bogus=1)


@pytest.mark.skipif(not SKLEARN_INSTALLED, reason="sklearn not installed")
def test_sklearn_cross_val_integration():
    from sklearn.model_selection import cross_val_score
    X, y = _reg_data(n=300)
    scores = cross_val_score(
        XGBRegressor(n_estimators=40, max_depth=3, learning_rate=0.3), X, y,
        cv=3, scoring="neg_mean_squared_error")
    assert scores.mean() > -0.2


def test_apply_leaf_indices():
    X, y = _reg_data(n=200)
    model = XGBRegressor(n_estimators=5, max_depth=3).fit(X, y)
    leaves = model.apply(X)
    assert leaves.shape == (200, 5)


def test_classifier_refit_binary_after_multiclass():
    """Regression: a multiclass fit must not poison a later binary fit."""
    rng = np.random.RandomState(4)
    X3 = rng.rand(300, 4).astype(np.float32)
    y3 = (X3[:, 0] * 3).astype(int)
    X2 = rng.rand(300, 4).astype(np.float32)
    y2 = (X2[:, 0] > 0.5).astype(int)
    model = XGBClassifier(n_estimators=5, max_depth=3)
    model.fit(X3, y3)
    model.fit(X2, y2)
    proba = model.predict_proba(X2)
    assert proba.shape == (300, 2)
    assert (model.predict(X2) == y2).mean() > 0.9


def test_early_stopping_without_eval_set_raises():
    X, y = _reg_data(n=100)
    with pytest.raises(ValueError, match="early stopping"):
        XGBRegressor(n_estimators=10).fit(X, y, early_stopping_rounds=3)

"""Mesh-fused training: the shard_map'd segmented round scan
(``Booster.update_many`` -> ``_scan_rounds_mesh``).

The contract under test is stronger than the per-round data-parallel
path's: with ``hist_precision=fixed`` (int32 fixed-point histogram
accumulation — exactly associative), the MODEL BYTES are bitwise
invariant to the data-mesh device count, eval lines included, and the
mesh-fused driver composes with checkpoints/warm starts and the gang
launcher exactly like the single-device fused driver.

Tests needing a live multi-device mesh gate on
``mesh_available(min_devices=...)`` — tests/conftest.py forces 8
virtual CPU devices in this container, so they run here; on a 1-device
host they skip like the existing mesh tests.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import xgboost_tpu as xgb  # noqa: E402
from xgboost_tpu.learner import Booster  # noqa: E402
from xgboost_tpu.obs import comm  # noqa: E402
from xgboost_tpu.obs.metrics import training_metrics  # noqa: E402
from xgboost_tpu.parallel.mesh import (data_parallel_mesh,  # noqa: E402
                                       mesh_available, set_mesh)

PARAMS = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.4,
          "max_bin": 16, "hist_precision": "fixed", "dsplit": "row"}


def make_data(n=512, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


def _train_fused(n_devices, n_rounds=5, k=None, params=PARAMS):
    """Train through update_many on an ``n_devices`` mesh (``None`` =
    mesh-free); returns (model bytes, eval lines)."""
    X, y = make_data()
    Xe, ye = make_data(n=256, seed=7)
    set_mesh(None if n_devices is None
             else data_parallel_mesh(n_devices))
    try:
        d = xgb.DMatrix(X, label=y)
        de = xgb.DMatrix(Xe, label=ye)
        bst = Booster(params, cache=[d, de])
        lines = []
        bst.update_many(d, 0, n_rounds,
                        evals=[(d, "train"), (de, "eval")],
                        eval_callback=lambda i, m: lines.append(m),
                        rounds_per_dispatch=k)
        return bytes(bst.save_raw()), lines
    finally:
        set_mesh(None)


@pytest.mark.skipif(not mesh_available(2), reason="needs >= 2 devices")
@pytest.mark.parametrize("n_devices", [2, 8])
def test_model_bytes_invariant_to_device_count(n_devices):
    """The acceptance contract: a >= 2-device mesh-fused run produces
    model bytes BIT-identical to the single-device fused run on the
    same data — eval-line text included (fixed-point histograms make
    the cross-shard reduction exactly associative)."""
    if not mesh_available(n_devices):
        pytest.skip(f"needs >= {n_devices} devices")
    ref_model, ref_lines = _train_fused(1)
    got_model, got_lines = _train_fused(n_devices)
    assert got_lines == ref_lines
    assert got_model == ref_model


@pytest.mark.skipif(not mesh_available(4), reason="needs >= 4 devices")
def test_mesh_fused_matches_mesh_free_fused():
    """The mesh driver also bit-matches the MESH-FREE fused scan (same
    params, no mesh installed): integer histogram sums are
    permutation-invariant, so sharded accumulation + psum equals
    single-array accumulation."""
    ref_model, ref_lines = _train_fused(None)
    got_model, got_lines = _train_fused(4)
    assert got_lines == ref_lines
    assert got_model == ref_model


@pytest.mark.skipif(not mesh_available(4), reason="needs >= 4 devices")
def test_segment_size_invariance_and_psum_accounting():
    """Segment size must not change the model, and the mesh-fused path
    must account its REAL collectives: max_depth psums per tree-growth
    step with the whole-tree payload estimate, zero allreduce charges,
    zero seconds (the psums execute inside the fused program)."""
    n_rounds, depth, f, bins = 6, PARAMS["max_depth"], 10, PARAMS["max_bin"]
    ref_model, ref_lines = _train_fused(4, n_rounds=n_rounds, k=None)
    before = comm.totals()
    got_model, got_lines = _train_fused(4, n_rounds=n_rounds, k=2)
    after = comm.totals()
    assert got_model == ref_model and got_lines == ref_lines
    assert (after["psum"]["count"] - before["psum"]["count"]
            == n_rounds * depth)
    per_tree = ((1 << depth) - 1) * f * bins * 2 * 4
    assert (after["psum"]["bytes"] - before["psum"]["bytes"]
            == n_rounds * per_tree)
    assert after["psum"]["seconds"] == before["psum"]["seconds"]
    # satellite contract: the dispatch wall is NOT charged to allreduce
    assert after["allreduce"]["count"] == before["allreduce"]["count"]
    assert after["allreduce"]["seconds"] == before["allreduce"]["seconds"]


@pytest.mark.skipif(not mesh_available(4), reason="needs >= 4 devices")
def test_checkpoint_warm_start_bit_identity():
    """Segment-boundary checkpoint resume through the mesh-fused
    driver: stop after 4 of 6 rounds, reload from serialized bytes in
    a FRESH booster, finish — bitwise equal to the uninterrupted run
    (per-iteration fold_in seeding + associative histograms)."""
    X, y = make_data()
    set_mesh(data_parallel_mesh(4))
    try:
        d = xgb.DMatrix(X, label=y)
        full = Booster(PARAMS, cache=[d])
        full.update_many(d, 0, 6, rounds_per_dispatch=2)
        want = bytes(full.save_raw())

        head = Booster(PARAMS, cache=[d])
        head.update_many(d, 0, 4, rounds_per_dispatch=2)
        blob = head.save_raw()

        d2 = xgb.DMatrix(X, label=y)
        tail = Booster(PARAMS, cache=[d2])
        tail.load_raw(blob)
        tail.set_param(PARAMS)
        assert tail.gbtree.num_boosted_rounds == 4
        tail.update_many(d2, 4, 2, rounds_per_dispatch=2)
        assert bytes(tail.save_raw()) == want
    finally:
        set_mesh(None)


@pytest.mark.skipif(not mesh_available(2), reason="needs >= 2 devices")
@pytest.mark.parametrize("n_head,n_tail",
                         [(4, 2), (4, 1), (2, 1), (2, 4)])
def test_mesh_size_change_resume_parity(n_head, n_tail, tmp_path):
    """The degraded-mesh resume oracle (RECOVERY.md degraded-mode
    matrix): train 4 of 6 rounds on an ``n_head``-device mesh, write a
    segment-boundary RING checkpoint (the real cli ring writer), then
    resume the remaining rounds in a FRESH booster on an ``n_tail``-
    device mesh through the real ring loader — rows re-sharded to the
    new size.  Bytes AND eval-line text must equal the uninterrupted
    single-device run for every shrink the degrade ladder can take
    (4->2, 4->1, 2->1) and for the grow-back direction (2->4)."""
    if not mesh_available(max(n_head, n_tail)):
        pytest.skip(f"needs >= {max(n_head, n_tail)} devices")
    from xgboost_tpu.cli import _load_checkpoint, _save_checkpoint
    want_model, want_lines = _train_fused(1, n_rounds=6, k=2)

    X, y = make_data()
    Xe, ye = make_data(n=256, seed=7)
    ck = str(tmp_path / "ring")
    lines = []

    set_mesh(data_parallel_mesh(n_head))
    try:
        d = xgb.DMatrix(X, label=y)
        de = xgb.DMatrix(Xe, label=ye)
        head = Booster(PARAMS, cache=[d, de])
        head.update_many(d, 0, 4, evals=[(d, "train"), (de, "eval")],
                         eval_callback=lambda i, m: lines.append(m),
                         rounds_per_dispatch=2)
        _save_checkpoint(ck, head, 4)
    finally:
        set_mesh(None)

    set_mesh(data_parallel_mesh(n_tail))
    try:
        d2 = xgb.DMatrix(X, label=y)
        de2 = xgb.DMatrix(Xe, label=ye)
        tail = Booster(PARAMS, cache=[d2, de2])
        tail, start = _load_checkpoint(ck, tail, PARAMS)
        assert start == 4
        assert tail.gbtree.num_boosted_rounds == 4
        tail.update_many(d2, start, 6 - start,
                         evals=[(d2, "train"), (de2, "eval")],
                         eval_callback=lambda i, m: lines.append(m),
                         rounds_per_dispatch=2)
        assert lines == want_lines
        assert bytes(tail.save_raw()) == want_model
    finally:
        set_mesh(None)


def test_fused_fallback_is_loud(monkeypatch):
    """A multi-round run that cannot fuse must say so: the
    xgbtpu_train_fused_fallback_total counter gains the first blocking
    reason (here the XGBTPU_SEQ_BOOST escape hatch)."""
    monkeypatch.setenv("XGBTPU_SEQ_BOOST", "1")
    X, y = make_data(n=128)
    d = xgb.DMatrix(X, label=y)
    bst = Booster({"objective": "binary:logistic", "max_depth": 3,
                   "eta": 0.4, "max_bin": 16}, cache=[d])
    fb = training_metrics().fused_fallback
    base = fb.value("seq_boost_env")
    bst.update_many(d, 0, 3)
    assert fb.value("seq_boost_env") == base + 1


def test_fused_path_does_not_fall_back(monkeypatch):
    """The inverse guard: a plain eligible run increments NOTHING —
    chaos/bench runs rely on this counter staying flat to certify they
    measured the fused path."""
    monkeypatch.delenv("XGBTPU_SEQ_BOOST", raising=False)
    X, y = make_data(n=128)
    d = xgb.DMatrix(X, label=y)
    bst = Booster({"objective": "binary:logistic", "max_depth": 3,
                   "eta": 0.4, "max_bin": 16}, cache=[d])
    fb = training_metrics().fused_fallback
    base = sum(fb.values().values())
    bst.update_many(d, 0, 3)
    assert sum(fb.values().values()) == base


# --------------------------------------------------------------- launcher
def test_standalone_launcher_honors_local_devices(tmp_path):
    """init_worker must apply XGBTPU_LOCAL_DEVICES even WITHOUT a
    coordinator (the standalone gang path): the worker gets an
    in-process multi-device view — the live mesh target on hosts whose
    backend cannot execute multi-process programs."""
    from xgboost_tpu.parallel.launch import launch_local
    code = ("import os\n"
            "assert 'XGBTPU_COORD' not in os.environ\n"
            "from xgboost_tpu.parallel.launch import init_worker\n"
            "assert init_worker() is False\n"
            "import jax\n"
            "assert jax.device_count() == 3, jax.devices()\n")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    rc = subprocess.run(
        [sys.executable, "-c", code],
        env=dict(env, XGBTPU_LOCAL_DEVICES="3", JAX_PLATFORMS="cpu"),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        timeout=120).returncode
    assert rc == 0
    # and the launcher exports it from --local-devices in standalone
    # mode (mesh-free gang-watchdog smoke: worker runs, gang exits 0)
    rc = launch_local(
        1, [sys.executable, "-c", code], standalone=True, local_devices=3)
    assert rc == 0


def test_standalone_watchdog_kills_silent_gang():
    """Gang-watchdog smoke over the standalone plumbing: a worker that
    never heartbeats is killed at the stall window and, with no
    keepalive, launch_local returns STALL_RC."""
    from xgboost_tpu.parallel.launch import STALL_RC, launch_local
    t0 = time.monotonic()
    rc = launch_local(
        1, [sys.executable, "-c", "import time; time.sleep(60)"],
        standalone=True, keepalive=False, watchdog_stall_sec=1.5)
    assert rc == STALL_RC
    assert time.monotonic() - t0 < 30

"""Worker script for test_launch.py: multi-process distributed growth.

Each process loads its row shard of a deterministic dataset, assembles
the global row-sharded arrays via make_array_from_process_local_data,
grows one tree with the dp path (psum over the 4-device global mesh),
and rank 0 writes the tree arrays to OUT_PATH.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from xgboost_tpu.parallel.launch import init_worker  # noqa: E402

assert init_worker(local_device_count=2)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def main():
    out_path = sys.argv[1]
    rank = jax.process_index()
    nproc = jax.process_count()
    # 2 local devices per process; the GLOBAL mesh spans all processes
    assert jax.device_count() == 2 * nproc

    from xgboost_tpu.binning import bin_dense, compute_cuts
    from xgboost_tpu.data import DMatrix
    from xgboost_tpu.models.gbtree import make_grow_config
    from xgboost_tpu.config import TrainParam
    from xgboost_tpu.parallel.dp import grow_tree_dp
    from xgboost_tpu.parallel.mesh import data_parallel_mesh

    # deterministic dataset; every process derives the same cuts from the
    # full data ONLY to keep the test self-contained (cut proposal across
    # hosts is parallel/sketch_device.py's job, tested separately)
    rng = np.random.RandomState(0)
    X = rng.rand(1024, 6).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0.8).astype(np.float32)
    cuts = compute_cuts(DMatrix(X, label=y), max_bin=16)
    param = TrainParam(max_depth=3, eta=0.5)
    cfg = make_grow_config(param, cuts.max_bin)

    # block row shard per process (the global array is the concatenation)
    n_local = X.shape[0] // nproc
    sl = slice(rank * n_local, (rank + 1) * n_local)
    binned_local = bin_dense(X[sl], cuts)
    margin_local = np.zeros(n_local, np.float32)
    p = 1.0 / (1.0 + np.exp(-margin_local))
    gh_local = np.stack([p - y[sl], p * (1 - p)], axis=1).astype(np.float32)

    mesh = data_parallel_mesh()

    def globalize(a):
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("data", *[None] * (a.ndim - 1))), a)

    binned = globalize(binned_local)
    gh = globalize(gh_local)
    rv = globalize(np.ones(n_local, bool))

    tree, row_leaf, delta = grow_tree_dp(
        mesh, jax.random.PRNGKey(7), binned, gh,
        jnp.asarray(cuts.cut_values), jnp.asarray(cuts.n_cuts), cfg, rv)

    if rank == 0:
        state = {f: np.asarray(getattr(tree, f)) for f in tree._fields}
        np.savez(out_path, **state)
    # all processes finish cleanly
    jax.experimental.multihost_utils.sync_global_devices("done")


if __name__ == "__main__":
    import jax.experimental.multihost_utils  # noqa: F401
    main()

"""xgtpu-lint v3: dataflow-aware rules XGT013-XGT015, contract rules
XGT016/XGT017, SARIF output, and the DonationGuard runtime twin
(ANALYSIS.md §v3, analysis/dataflow.py + analysis/contracts.py).

Layers:

1. **fixture snippets** — each dataflow rule fires on its known-bad
   snippet (including the ISSUE's pinned cases: the aliased donated
   buffer MUST fail, the carry rebind MUST pass, a psum over a renamed
   mesh axis MUST fail) and is silenced by ``# xgtpu: disable=``;
2. **contract mini-trees** — XGT016 (exit-code registry) and XGT017
   (event-name drift) fire on bad trees and stay quiet on good twins,
   and their ``exit_codes``/``events`` inventory sections round-trip;
3. **enforcement** — the tier-1 gate: the whole repo is clean under
   XGT013-XGT017 with an EMPTY baseline (debt was fixed, not
   baselined);
4. **SARIF** — ``--sarif`` emits valid SARIF 2.1.0 whose results
   round-trip against ``--json`` (same findings, same exit contract);
5. **runtime twin** — DonationGuard gives CPU the device's donation
   semantics, and an integration run drives the REAL fused
   ``_scan_rounds`` dispatch under it: the tree's carry discipline
   holds in execution, not just in the AST.

Everything except the DonationGuard integration test is pure
stdlib-AST work; that one runs a tiny CPU training job.
"""

import json
import os

import numpy as np
import pytest

from xgboost_tpu.analysis import analyze_source
from xgboost_tpu.analysis.__main__ import main as lint_main
from xgboost_tpu.analysis.contracts import ContractEngine
from xgboost_tpu.analysis.rules import rules_by_code

PKG_DIR = os.path.dirname(os.path.abspath(__import__(
    "xgboost_tpu").__file__))
REPO_ROOT = os.path.dirname(PKG_DIR)
TOOLS_DIR = os.path.join(REPO_ROOT, "tools")


def codes(src, only, path="xgboost_tpu/models/gbtree.py"):
    """Codes from ``only`` firing on a snippet (rule-filtered, so a
    fixture for one rule cannot leak hits from another)."""
    active, _ = analyze_source(src, path=path, rules=rules_by_code(only))
    return sorted({f.rule for f in active})


def findings(src, only, path="xgboost_tpu/models/gbtree.py"):
    active, _ = analyze_source(src, path=path, rules=rules_by_code(only))
    return active


def suppressed(src, only, path="xgboost_tpu/models/gbtree.py"):
    _, sup = analyze_source(src, path=path, rules=rules_by_code(only))
    return sorted({f.rule for f in sup})


# ----------------------------------------------------------------- XGT013
class TestUseAfterDonate:
    def test_read_after_donate_fires(self):
        bad = ("import jax\n"
               "fn = jax.jit(impl, donate_argnums=(0,))\n"
               "def run(m):\n"
               "    out = fn(m, 3)\n"
               "    return m.sum()\n")
        fs = findings(bad, ["XGT013"])
        assert [f.rule for f in fs] == ["XGT013"]
        assert fs[0].line == 5  # anchored at the dead READ, not the call

    def test_carry_rebind_must_pass(self):
        good = ("import jax\n"
                "fn = jax.jit(impl, donate_argnums=(0,))\n"
                "def run(m):\n"
                "    m = fn(m, 3)\n"
                "    return m\n")
        assert codes(good, ["XGT013"]) == []

    def test_aliased_donated_buffer_must_fail(self):
        # the ISSUE's pinned MUST-FAIL: the carry rebind revives the
        # NAME, but `keep` still points at the dead buffer
        bad = ("import jax\n"
               "fn = jax.jit(impl, donate_argnums=(0,))\n"
               "def run(m):\n"
               "    keep = m\n"
               "    m = fn(m, 3)\n"
               "    return keep.sum()\n")
        fs = findings(bad, ["XGT013"])
        assert [f.rule for f in fs] == ["XGT013"]
        assert "alias" in fs[0].message

    def test_loop_without_rebind_fires(self):
        bad = ("import jax\n"
               "fn = jax.jit(impl, donate_argnums=(0,))\n"
               "def run(m):\n"
               "    for i in range(3):\n"
               "        out = fn(m, i)\n"
               "    return out\n")
        assert codes(bad, ["XGT013"]) == ["XGT013"]

    def test_loop_with_carry_rebind_is_clean(self):
        good = ("import jax\n"
                "fn = jax.jit(impl, donate_argnums=(0,))\n"
                "def run(m):\n"
                "    for i in range(3):\n"
                "        m = fn(m, i)\n"
                "    return m\n")
        assert codes(good, ["XGT013"]) == []

    def test_redefinition_revives_the_name(self):
        good = ("import jax\n"
                "fn = jax.jit(impl, donate_argnums=(0,))\n"
                "def run(m):\n"
                "    out = fn(m, 3)\n"
                "    m = out * 2\n"
                "    return m.sum()\n")
        assert codes(good, ["XGT013"]) == []

    def test_gbtree_shape_conditional_wrapper_and_tuple(self):
        # the real call shape: partial(jax.jit,..)(impl) definition,
        # `scan = donated if flag else plain` selection, tuple-wrapped
        # pytree at a donated position, results bound to fresh names,
        # donated names never read again
        good = (
            "import functools, jax\n"
            "_donated = functools.partial(\n"
            "    jax.jit, static_argnames=('k',),\n"
            "    donate_argnums=(1, 3))(impl)\n"
            "_plain = jax.jit(impl)\n"
            "def run(data, margin, emargins, flag):\n"
            "    scan = _donated if flag else _plain\n"
            "    margin_f, eouts = scan(data, margin, 0,\n"
            "                           tuple(emargins), k=4)\n"
            "    return margin_f, eouts\n")
        assert codes(good, ["XGT013"]) == []
        bad = good.replace("    return margin_f, eouts\n",
                           "    return margin.sum()\n")
        assert codes(bad, ["XGT013"]) == ["XGT013"]

    def test_suppression_silences(self):
        bad = ("import jax\n"
               "fn = jax.jit(impl, donate_argnums=(0,))\n"
               "def run(m):\n"
               "    out = fn(m, 3)\n"
               "    return m.sum()  # xgtpu: disable=XGT013\n")
        assert codes(bad, ["XGT013"]) == []
        assert suppressed(bad, ["XGT013"]) == ["XGT013"]


# ----------------------------------------------------------------- XGT014
class TestImpureTracedScope:
    def test_event_time_print_in_jit_fire(self):
        bad = ("import jax, time\n"
               "from xgboost_tpu.obs import trace\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    trace.event('train.step', n=1)\n"
               "    t = time.time()\n"
               "    print(x)\n"
               "    return x * 2\n")
        fs = findings(bad, ["XGT014"])
        assert len(fs) == 3 and {f.rule for f in fs} == {"XGT014"}

    def test_global_mutation_fires(self):
        bad = ("import jax\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    global N\n"
               "    N = 1\n"
               "    return x\n")
        assert codes(bad, ["XGT014"]) == ["XGT014"]

    def test_np_asarray_on_traced_fires_static_kwonly_clean(self):
        src = ("import jax\n"
               "import numpy as np\n"
               "@jax.jit\n"
               "def step(x, *, k):\n"
               "    a = np.asarray(x)\n"
               "    b = np.asarray(k)\n"
               "    return a\n")
        fs = findings(src, ["XGT014"])
        assert len(fs) == 1 and fs[0].line == 5  # only the traced arg

    def test_jax_debug_is_exempt(self):
        good = ("import jax\n"
                "@jax.jit\n"
                "def step(x):\n"
                "    jax.debug.print('x={x}', x=x)\n"
                "    return x * 2\n")
        assert codes(good, ["XGT014"]) == []

    def test_host_side_code_is_clean(self):
        good = ("import time\n"
                "from xgboost_tpu.obs import trace\n"
                "def host(x):\n"
                "    trace.event('train.done', n=1)\n"
                "    print(x, time.time())\n")
        assert codes(good, ["XGT014"]) == []

    def test_scan_body_is_traced(self):
        # passed to lax.scan by name, not jit-decorated: still traced,
        # and so is a def nested inside it
        bad = ("import jax\n"
               "def train(xs):\n"
               "    def body(carry, x):\n"
               "        print(x)\n"
               "        return carry, x\n"
               "    return jax.lax.scan(body, 0, xs)\n")
        assert codes(bad, ["XGT014"]) == ["XGT014"]

    def test_suppression_silences(self):
        bad = ("import jax\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    print(x)  # xgtpu: disable=XGT014 -- trace-time probe\n"
               "    return x\n")
        assert codes(bad, ["XGT014"]) == []
        assert suppressed(bad, ["XGT014"]) == ["XGT014"]


# ----------------------------------------------------------------- XGT015
SHARD_SRC = ("import jax\n"
             "from jax.sharding import PartitionSpec as P\n"
             "DATA_AXIS = 'data'\n"
             "def body(x):\n"
             "    return jax.lax.psum(x, {axis})\n"
             "def run(mesh, x):\n"
             "    f = shard_map(body, mesh=mesh,\n"
             "                  in_specs=(P(DATA_AXIS),),\n"
             "                  out_specs=P(DATA_AXIS))\n"
             "    return f(x)\n")


class TestCollectiveAxisDiscipline:
    def test_renamed_axis_must_fail(self):
        # the ISSUE's pinned MUST-FAIL: psum over an axis name the
        # enclosing shard_map's specs never mention
        fs = findings(SHARD_SRC.format(axis="'batch'"), ["XGT015"])
        assert [f.rule for f in fs] == ["XGT015"]
        assert "'batch'" in fs[0].message

    def test_constant_resolved_axis_passes(self):
        assert codes(SHARD_SRC.format(axis="DATA_AXIS"), ["XGT015"]) == []
        assert codes(SHARD_SRC.format(axis="'data'"), ["XGT015"]) == []

    def test_imported_constant_matches_symbolically(self):
        # DATA_AXIS imported, not defined in-file: both sides
        # canonicalize to $DATA_AXIS and match
        src = SHARD_SRC.replace("DATA_AXIS = 'data'\n", "")
        assert codes("from xgboost_tpu.parallel.mesh import DATA_AXIS\n"
                     + src.format(axis="DATA_AXIS"), ["XGT015"]) == []

    def test_param_axis_is_skipped(self):
        # axis name flowing in as a parameter is a config seam the
        # static rule cannot judge — skipped, not guessed
        src = ("import jax\n"
               "from jax.sharding import PartitionSpec as P\n"
               "def body(x, *, axis_name):\n"
               "    return jax.lax.psum(x, axis_name)\n"
               "def run(mesh, x):\n"
               "    f = shard_map(body, mesh=mesh, in_specs=(P('data'),),\n"
               "                  out_specs=P('data'))\n"
               "    return f(x)\n")
        assert codes(src, ["XGT015"]) == []

    def test_collective_under_traced_branch_fires(self):
        bad = ("import jax\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    if x.sum() > 0:\n"
               "        x = jax.lax.psum(x, 'data')\n"
               "    return x\n")
        fs = findings(bad, ["XGT015"])
        assert [f.rule for f in fs] == ["XGT015"]
        assert "trace time" in fs[0].message

    def test_static_tests_are_exempt(self):
        good = ("import jax\n"
                "@jax.jit\n"
                "def step(x, *, use_dp):\n"
                "    if use_dp and x.ndim > 1:\n"
                "        x = jax.lax.psum(x, 'data')\n"
                "    if x is None:\n"
                "        return x\n"
                "    return x\n")
        assert codes(good, ["XGT015"]) == []

    def test_suppression_silences(self):
        bad = SHARD_SRC.format(axis="'batch'").replace(
            "psum(x, 'batch')",
            "psum(x, 'batch')  # xgtpu: disable=XGT015")
        assert codes(bad, ["XGT015"]) == []
        assert suppressed(bad, ["XGT015"]) == ["XGT015"]


# ----------------------------------------------------------------- XGT016
RC_SRC = ("FENCE_RC = 143\n"
          "HOST_LOSS_RC = 144\n")


def contract_run(tmp_path, codes_):
    eng = ContractEngine(str(tmp_path), codes=codes_)
    return eng.run()[0], eng


class TestExitCodeRegistry:
    def _registry(self, tmp_path):
        (tmp_path / "reliability").mkdir()
        (tmp_path / "reliability" / "rc.py").write_text(RC_SRC)

    def test_magic_literal_for_registered_code_fires(self, tmp_path):
        self._registry(tmp_path)
        (tmp_path / "w.py").write_text(
            "import os\ndef die():\n    os._exit(143)\n")
        act, _ = contract_run(tmp_path, {"XGT016"})
        assert len(act) == 1 and "FENCE_RC" in act[0].message

    def test_unregistered_protocol_code_fires(self, tmp_path):
        self._registry(tmp_path)
        (tmp_path / "w.py").write_text(
            "import sys\ndef die():\n    sys.exit(77)\n")
        act, _ = contract_run(tmp_path, {"XGT016"})
        assert len(act) == 1 and "unregistered" in act[0].message

    def test_generic_posix_codes_exempt(self, tmp_path):
        self._registry(tmp_path)
        (tmp_path / "w.py").write_text(
            "import sys\n"
            "def die(bad):\n"
            "    sys.exit(2 if bad else 0)\n"
            "def fail():\n"
            "    sys.exit(1)\n")
        act, _ = contract_run(tmp_path, {"XGT016"})
        assert act == []

    def test_rc_constant_outside_registry_fires(self, tmp_path):
        self._registry(tmp_path)
        (tmp_path / "w.py").write_text("MY_FAIL_RC = 99\n")
        act, _ = contract_run(tmp_path, {"XGT016"})
        assert len(act) == 1 and "outside the registry" in act[0].message

    def test_returncode_compare_against_literal_fires(self, tmp_path):
        self._registry(tmp_path)
        (tmp_path / "w.py").write_text(
            "def classify(p):\n"
            "    if p.returncode == 143:\n"
            "        return 'fence'\n"
            "    return 'ok' if p.returncode == 0 else 'crash'\n")
        act, _ = contract_run(tmp_path, {"XGT016"})
        # only the registered 143 fires; rc == 0 is out of scope
        assert len(act) == 1 and "143" in act[0].message

    def test_symbolic_usage_is_clean(self, tmp_path):
        self._registry(tmp_path)
        (tmp_path / "w.py").write_text(
            "import os\n"
            "from reliability.rc import FENCE_RC\n"
            "def die():\n    os._exit(FENCE_RC)\n"
            "def classify(p):\n    return p.returncode == FENCE_RC\n")
        act, _ = contract_run(tmp_path, {"XGT016"})
        assert act == []

    def test_duplicate_registration_fires(self, tmp_path):
        (tmp_path / "reliability").mkdir()
        (tmp_path / "reliability" / "rc.py").write_text(
            "A_RC = 143\nB_RC = 143\n")
        act, _ = contract_run(tmp_path, {"XGT016"})
        assert len(act) == 1 and "twice" in act[0].message

    def test_exit_codes_inventory_section(self, tmp_path):
        self._registry(tmp_path)
        _, eng = contract_run(tmp_path, {"XGT016"})
        assert eng.inventory()["exit_codes"] == {
            "FENCE_RC": 143, "HOST_LOSS_RC": 144}

    def test_real_registry_matches_inventory(self):
        from xgboost_tpu.reliability import rc
        eng = ContractEngine(REPO_ROOT, fact_paths=[PKG_DIR, TOOLS_DIR])
        assert eng.inventory()["exit_codes"] == rc.registry()
        assert len(rc.registry()) >= 6


# ----------------------------------------------------------------- XGT017
EVENT_DOC = ("# obs\n"
             "## Event inventory\n"
             "| event | emitted when |\n"
             "|---|---|\n"
             "| `gang.fence` | self-fence |\n"
             "| `pipeline.{gate,publish}` | lifecycle |\n"
             "## Next section\n"
             "prose mention of `other.event` does not count\n")


class TestEventNameDrift:
    def test_undocumented_event_fires_at_emit_site(self, tmp_path):
        (tmp_path / "OBSERVABILITY.md").write_text(EVENT_DOC)
        (tmp_path / "w.py").write_text(
            "from xgboost_tpu.obs import trace\n"
            "def go():\n"
            "    trace.event('gang.fence', rank=1)\n"
            "    trace.event('pipeline.gate')\n"
            "    trace.event('pipeline.publish')\n"
            "    trace.event('gang.mystery', rank=1)\n")
        act, _ = contract_run(tmp_path, {"XGT017"})
        assert len(act) == 1
        assert "gang.mystery" in act[0].message and act[0].line == 6

    def test_stale_doc_row_fires_at_doc_line(self, tmp_path):
        (tmp_path / "OBSERVABILITY.md").write_text(EVENT_DOC)
        (tmp_path / "w.py").write_text(
            "from xgboost_tpu.obs import trace\n"
            "def go():\n"
            "    trace.event('gang.fence')\n"
            "    trace.event('pipeline.gate')\n"
            "    trace.event('pipeline.publish')\n")
        act, _ = contract_run(tmp_path, {"XGT017"})
        assert act == []  # brace expansion covered both pipeline rows
        (tmp_path / "w.py").write_text(
            "from xgboost_tpu.obs import trace\n"
            "def go():\n    trace.event('pipeline.gate')\n"
            "    trace.event('pipeline.publish')\n")
        act, _ = contract_run(tmp_path, {"XGT017"})
        assert len(act) == 1 and "gang.fence" in act[0].message
        assert act[0].path.endswith("OBSERVABILITY.md")

    def test_heading_scoping_ignores_prose_and_spans(self, tmp_path):
        # `other.event` appears in backticks OUTSIDE the inventory
        # heading: emitting it must still be a finding
        (tmp_path / "OBSERVABILITY.md").write_text(EVENT_DOC)
        (tmp_path / "w.py").write_text(
            "from xgboost_tpu.obs import trace\n"
            "def go():\n"
            "    trace.event('gang.fence')\n"
            "    trace.event('pipeline.gate')\n"
            "    trace.event('pipeline.publish')\n"
            "    trace.event('other.event')\n")
        act, _ = contract_run(tmp_path, {"XGT017"})
        assert len(act) == 1 and "other.event" in act[0].message

    def test_emit_dict_kind_event_counts_span_does_not(self, tmp_path):
        (tmp_path / "OBSERVABILITY.md").write_text(EVENT_DOC)
        (tmp_path / "w.py").write_text(
            "def go(events):\n"
            "    events.emit({'kind': 'event', 'name': 'x.y', 'n': 1})\n"
            "    events.emit({'kind': 'span', 'name': 'span.name'})\n")
        act, eng = contract_run(tmp_path, {"XGT017"})
        emitted = {n for _, n, _ in eng.facts().events}
        assert emitted == {"x.y"}
        msgs = [f.message for f in act]
        assert any("x.y" in m for m in msgs)
        assert not any("span.name" in m for m in msgs)

    def test_real_tree_roundtrips(self):
        from xgboost_tpu.analysis.contracts import _doc_event_table
        eng = ContractEngine(REPO_ROOT, fact_paths=[PKG_DIR, TOOLS_DIR])
        emitted = {n for _, n, _ in eng.facts().events}
        with open(os.path.join(REPO_ROOT, "OBSERVABILITY.md")) as f:
            documented = set(_doc_event_table(f.read()))
        assert emitted, "event extraction found nothing — collector broke"
        assert emitted == documented
        assert set(eng.inventory()["events"]) == emitted


# ------------------------------------------------------- inventory drift
class TestInventoryDrift:
    def _seeded(self, tmp_path):
        (tmp_path / "reliability").mkdir()
        (tmp_path / "reliability" / "rc.py").write_text(RC_SRC)
        (tmp_path / "OBSERVABILITY.md").write_text(EVENT_DOC)
        (tmp_path / "w.py").write_text(
            "from xgboost_tpu.obs import trace\n"
            "def go():\n"
            "    trace.event('gang.fence')\n"
            "    trace.event('pipeline.gate')\n"
            "    trace.event('pipeline.publish')\n")
        eng = ContractEngine(str(tmp_path), codes={"XGT016", "XGT017"})
        eng.write_inventory()
        return eng

    def test_fresh_inventory_is_clean(self, tmp_path):
        self._seeded(tmp_path)
        act, _ = contract_run(tmp_path, {"XGT016", "XGT017"})
        assert act == []

    def test_unregistered_addition_drifts_each_section(self, tmp_path):
        self._seeded(tmp_path)
        path = tmp_path / "ANALYSIS_CONTRACTS.json"
        committed = json.loads(path.read_text())
        committed["exit_codes"]["ROGUE_RC"] = 99
        committed["events"].append("rogue.event")
        path.write_text(json.dumps(committed))
        act, _ = contract_run(tmp_path, {"XGT016", "XGT017"})
        assert sorted(f.rule for f in act) == ["XGT016", "XGT017"]
        assert all("stale" in f.message for f in act)

    def test_drift_findings_scope_to_enabled_codes(self, tmp_path):
        self._seeded(tmp_path)
        path = tmp_path / "ANALYSIS_CONTRACTS.json"
        committed = json.loads(path.read_text())
        committed["events"].append("rogue.event")
        path.write_text(json.dumps(committed))
        act, _ = contract_run(tmp_path, {"XGT016"})
        assert act == []  # the drifted section belongs to XGT017


# ------------------------------------------------------------ enforcement
class TestWholeTreeClean:
    def test_dataflow_rules_clean_over_repo(self):
        rc = lint_main([PKG_DIR, TOOLS_DIR, "--rules",
                        "XGT013,XGT014,XGT015", "--no-baseline"])
        assert rc == 0

    def test_contract_rules_clean_over_repo(self):
        rc = lint_main(["--rules", "XGT016,XGT017", "--no-baseline",
                        PKG_DIR])
        assert rc == 0

    def test_baseline_is_empty(self):
        # the v3 ISSUE's bar: every finding was FIXED (or inline-
        # suppressed with a rationale), none accepted as debt
        path = os.path.join(REPO_ROOT, "ANALYSIS_BASELINE.json")
        with open(path) as f:
            assert json.load(f)["findings"] == {}

    def test_committed_inventory_has_v3_sections(self):
        path = os.path.join(REPO_ROOT, "ANALYSIS_CONTRACTS.json")
        with open(path) as f:
            inv = json.load(f)
        assert inv["exit_codes"] and inv["events"]
        assert inv["exit_codes"]["FENCE_RC"] == 143


# ------------------------------------------------------------------ SARIF
BAD_SARIF_SRC = ("import jax\n"
                 "fn = jax.jit(impl, donate_argnums=(0,))\n"
                 "def run(m):\n"
                 "    out = fn(m, 3)\n"
                 "    return m.sum()\n"
                 "@jax.jit\n"
                 "def step(x):\n"
                 "    print(x)\n"
                 "    return x\n")


class TestSarif:
    def test_sarif_roundtrips_against_json(self, tmp_path, capsys):
        p = tmp_path / "bad.py"
        p.write_text(BAD_SARIF_SRC)
        argv = [str(p), "--no-baseline", "--no-contracts"]
        rc_sarif = lint_main(argv + ["--sarif"])
        sarif = json.loads(capsys.readouterr().out)
        rc_json = lint_main(argv + ["--json"])
        plain = json.loads(capsys.readouterr().out)
        assert rc_sarif == rc_json == 1  # same exit contract
        assert sarif["version"] == "2.1.0"
        assert "sarif-2.1.0" in sarif["$schema"]
        # one run per rule code, each self-describing
        run_rules = [r["tool"]["driver"]["rules"][0]["id"]
                     for r in sarif["runs"]]
        assert run_rules == sorted(run_rules)
        assert set(run_rules) == {"XGT013", "XGT014"}
        flat = {(res["ruleId"],
                 res["locations"][0]["physicalLocation"]["region"]
                    ["startLine"],
                 res["message"]["text"])
                for run in sarif["runs"] for res in run["results"]}
        expect = {(f["rule"], f["line"], f["message"])
                  for f in plain["findings"]}
        assert flat == expect and flat

    def test_clean_tree_emits_catalog_run(self, tmp_path, capsys):
        p = tmp_path / "ok.py"
        p.write_text("X = 1\n")
        rc = lint_main([str(p), "--no-baseline", "--no-contracts",
                        "--sarif"])
        sarif = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert len(sarif["runs"]) == 1
        run = sarif["runs"][0]
        assert run["results"] == []
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        # the full catalog rides along so consumers can tell "ran
        # clean" from "didn't run"
        for code in ("XGT001", "XGT013", "XGT016", "XGT017"):
            assert code in rule_ids

    def test_json_and_sarif_are_mutually_exclusive(self, tmp_path):
        p = tmp_path / "ok.py"
        p.write_text("X = 1\n")
        assert lint_main([str(p), "--json", "--sarif"]) == 2


# ---------------------------------------------------------- DonationGuard
jax = pytest.importorskip("jax")


class TestDonationGuard:
    def test_post_call_touch_raises(self):
        import jax.numpy as jnp

        from xgboost_tpu.analysis.runtime import DonationGuard
        fn = jax.jit(lambda m, k: m + k, donate_argnums=(0,))
        guard = DonationGuard(donate_argnums=(0,))
        wrapped = guard.wrap(fn)
        m = jnp.ones((8,))
        out = wrapped(m, 2.0)
        assert guard.calls == 1
        assert float(out[0]) == 3.0
        with pytest.raises(RuntimeError, match="deleted"):
            m.sum()
        guard.assert_clean()  # the violation is the CALLER's, and raised

    def test_reuse_of_donated_buffer_is_recorded(self):
        import jax.numpy as jnp

        from xgboost_tpu.analysis.runtime import DonationGuard
        fn = jax.jit(lambda m, k: m + k, donate_argnums=(0,))
        guard = DonationGuard(donate_argnums=(0,))
        wrapped = guard.wrap(fn)
        m = jnp.ones((8,))
        wrapped(m, 2.0)
        with pytest.raises(RuntimeError):
            wrapped(m, 2.0)  # jax itself refuses the dead buffer...
        with pytest.raises(AssertionError, match="donated-reuse"):
            guard.assert_clean()  # ...and the guard names the hazard

    def test_non_donatable_position_is_recorded(self):
        from xgboost_tpu.analysis.runtime import DonationGuard
        fn = jax.jit(lambda m, k: m + k, donate_argnums=(0,))
        guard = DonationGuard(donate_argnums=(1,))
        guard.wrap(fn)(jax.numpy.ones((4,)), 2.0)  # pos 1 is a scalar
        with pytest.raises(AssertionError, match="non-donatable"):
            guard.assert_clean()

    def test_empty_pytree_at_donated_position_is_vacuously_fine(self):
        # gbtree donates tuple(eval_margins) unconditionally; a
        # no-evals run passes () there — nothing to donate, no noise
        from xgboost_tpu.analysis.runtime import DonationGuard
        fn = jax.jit(lambda m, ems: m * 2, donate_argnums=(0, 1))
        guard = DonationGuard(donate_argnums=(0, 1))
        guard.wrap(fn)(jax.numpy.ones((4,)), ())
        assert guard.calls == 1
        guard.assert_clean()

    @pytest.mark.filterwarnings("ignore:Some donated buffers")
    def test_real_scan_rounds_dispatch_is_donation_clean(self,
                                                         monkeypatch):
        """The runtime cross-check of XGT013 over the REAL fused path:
        wrap ``_scan_rounds_donated`` so CPU deletes donated buffers
        like a TPU reuses them, force the donated path on, and train
        multi-segment with evals — if ``do_boost_fused`` (or anything
        downstream) read a donated margin after dispatch, this run
        would raise 'Array has been deleted'."""
        import xgboost_tpu as xgb
        from xgboost_tpu.analysis.runtime import DonationGuard
        from xgboost_tpu.learner import Booster
        from xgboost_tpu.models import gbtree

        guard = DonationGuard(donate_argnums=(1, 11))
        monkeypatch.setattr(
            gbtree, "_scan_rounds_donated",
            guard.wrap(gbtree._scan_rounds_donated))
        monkeypatch.setenv("XGBTPU_FUSED_DONATE", "1")

        rng = np.random.RandomState(0)
        X = rng.rand(400, 6).astype(np.float32)
        y = (X[:, 0] > 0.5).astype(np.float32)
        dtrain = xgb.DMatrix(X, label=y)
        Xe = rng.rand(100, 6).astype(np.float32)
        deval = xgb.DMatrix(Xe, label=(Xe[:, 0] > 0.5).astype(np.float32))
        bst = Booster({"objective": "binary:logistic", "max_depth": 3,
                       "eta": 0.3, "eval_metric": "logloss"},
                      cache=[dtrain, deval])
        lines = []
        bst.update_many(dtrain, 0, 6,
                        evals=[(dtrain, "train"), (deval, "eval")],
                        eval_callback=lambda i, msg: lines.append(msg),
                        rounds_per_dispatch=3)
        assert guard.calls >= 2    # 6 rounds / 3 per dispatch
        assert len(lines) == 6     # eval lines came from live buffers
        guard.assert_clean()
        preds = np.asarray(bst.predict(dtrain))  # post-run predict OK
        assert preds.shape == (400,)

"""Device-side distributed sketch (parallel/sketch_device.py).

Validates the fixed-shape padded summary against the host sketch
(:mod:`xgboost_tpu.sketch`): WQSummary invariants (reference
``quantile.h:165-173``), the rank-error bound of merge+prune, cut
proposal rank-parity under mesh sharding, determinism, and end-to-end
dsplit=row training with ``device_sketch=1``.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from xgboost_tpu.parallel.sketch_device import (  # noqa: E402
    local_summary, merge_summaries_dev, propose_cuts_dev, sketch_cuts_mesh)
from xgboost_tpu.sketch import (make_summary, merge_summaries,  # noqa: E402
                                propose_cuts, prune_summary)


def _invariants(s, eps=1e-2):
    v = np.asarray(s.value)
    real = np.isfinite(v)
    v, rmin, rmax, wmin = (np.asarray(x)[real] for x in
                           (s.value, s.rmin, s.rmax, s.wmin))
    assert (rmin + wmin <= rmax + eps).all(), "rmin+wmin > rmax"
    assert (rmin >= -eps).all() and (wmin >= -eps).all()
    assert (np.diff(v) > 0).all(), "values not strictly increasing"
    assert (np.diff(rmin) >= -eps).all() and (np.diff(rmax) >= -eps).all()
    return v, rmin, rmax, wmin


def _max_rank_err(rmin, rmax, wmin):
    prev_rmax = np.concatenate([[0.0], rmax[:-1]])
    return float(np.maximum(rmin + wmin - prev_rmax,
                            rmax - rmin - wmin).max())


def test_local_summary_invariants_and_bound():
    rng = np.random.RandomState(0)
    v = rng.exponential(1.0, 5000).astype(np.float32)
    K = 64
    s = local_summary(jnp.asarray(v), jnp.ones(5000), K)
    _, rmin, rmax, wmin = _invariants(s)
    assert abs(float(s.rmax[-1]) - 5000) < 0.5
    # prune at size K keeps error <= ~2*total/K (WQSummary::SetPrune bound)
    assert _max_rank_err(rmin, rmax, wmin) <= 2.5 * 5000 / K


def test_local_summary_missing_and_duplicates():
    v = np.array([1.0, np.nan, 2.0, 1.0, np.inf, 2.0, 3.0], np.float32)
    s = local_summary(jnp.asarray(v), jnp.ones(7), 16)
    vals, rmin, rmax, wmin = _invariants(s)
    np.testing.assert_array_equal(vals, [1.0, 2.0, 3.0])
    np.testing.assert_array_equal(wmin, [2.0, 2.0, 1.0])
    assert float(s.rmax[-1]) == 5.0  # nan/inf rows dropped


def test_merge_matches_host_semantics():
    rng = np.random.RandomState(1)
    a = rng.randn(3000).astype(np.float32)
    b = (rng.randn(3000) * 2 + 1).astype(np.float32)
    K = 64
    da = local_summary(jnp.asarray(a), jnp.ones(3000), K)
    db = local_summary(jnp.asarray(b), jnp.ones(3000), K)
    dm = merge_summaries_dev(da, db, K)
    _, rmin, rmax, wmin = _invariants(dm)
    assert abs(float(dm.rmax[-1]) - 6000) < 1.0
    assert _max_rank_err(rmin, rmax, wmin) <= 3.0 * 6000 / K
    # cuts from the device merge rank-match the host merge within eps
    cuts_d = np.asarray(propose_cuts_dev(dm, 32))
    cuts_d = cuts_d[np.isfinite(cuts_d)]
    hm = prune_summary(merge_summaries(
        prune_summary(make_summary(a), K), prune_summary(make_summary(b), K)),
        K)
    cuts_h = propose_cuts(hm, 32)
    xs = np.sort(np.concatenate([a, b]))
    rd = np.searchsorted(xs, cuts_d) / 6000
    rh = np.searchsorted(xs, cuts_h) / 6000
    m = min(len(rd), len(rh))
    assert m >= 25
    assert np.abs(rd[:m] - rh[:m]).max() <= 2.0 / K + 0.02


def test_mesh_sketch_cuts_rank_parity(mesh8):
    from xgboost_tpu.binning import compute_cuts
    from xgboost_tpu.data import DMatrix
    rng = np.random.RandomState(0)
    X = rng.exponential(1.0, (20000, 5)).astype(np.float32)
    X[:, 2] = (X[:, 2] > 1.0)  # near-binary feature -> dense cut path
    eps = 0.05
    cuts_dev = sketch_cuts_mesh(mesh8, X, None, max_bin=32, sketch_eps=eps)
    cuts_host = compute_cuts(DMatrix(X), max_bin=32, sketch_eps=eps)
    for f in range(5):
        cd = cuts_dev.cut_values[f][:cuts_dev.n_cuts[f]]
        ch = cuts_host.cut_values[f][:cuts_host.n_cuts[f]]
        xs = np.sort(X[:, f])
        m = min(len(cd), len(ch))
        assert m >= min(len(ch), 2)
        rd = np.searchsorted(xs, cd[:m]) / len(xs)
        rh = np.searchsorted(xs, ch[:m]) / len(xs)
        assert np.abs(rd - rh).max() <= eps, f"feature {f}"
    # binary feature: exact dense cuts
    np.testing.assert_array_equal(
        cuts_dev.cut_values[2][:cuts_dev.n_cuts[2]], [0.0, 1.0])


def test_mesh_sketch_deterministic(mesh8):
    rng = np.random.RandomState(3)
    X = rng.randn(4096, 3).astype(np.float32)
    c1 = sketch_cuts_mesh(mesh8, X, None, max_bin=16)
    c2 = sketch_cuts_mesh(mesh8, X, None, max_bin=16)
    np.testing.assert_array_equal(c1.cut_values, c2.cut_values)
    np.testing.assert_array_equal(c1.n_cuts, c2.n_cuts)


def test_train_with_device_sketch(mesh8):
    import xgboost_tpu as xgb
    rng = np.random.RandomState(0)
    X = rng.rand(2000, 8).astype(np.float32)
    y = ((X[:, 0] + 0.5 * X[:, 1] > 0.75)).astype(np.float32)
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.5,
              "dsplit": "row", "max_bin": 32}
    ev_host, ev_dev = {}, {}
    xgb.train(params, xgb.DMatrix(X, label=y), 5,
              evals=[(xgb.DMatrix(X, label=y), "train")],
              evals_result=ev_host, verbose_eval=False)
    xgb.train({**params, "device_sketch": 1}, xgb.DMatrix(X, label=y), 5,
              evals=[(xgb.DMatrix(X, label=y), "train")],
              evals_result=ev_dev, verbose_eval=False)
    eh = float(ev_host["train-error"][-1])
    ed = float(ev_dev["train-error"][-1])
    assert ed <= eh + 0.02, (eh, ed)

"""CLI driver tests (reference demo/binary_classification/runexp.sh flow:
train with a config file, continue training, pred, dump, eval)."""

import os

import numpy as np
import pytest

from xgboost_tpu.cli import main as cli_main


def _write_libsvm(path, X, y):
    with open(path, "w") as f:
        for row, label in zip(X, y):
            feats = " ".join(f"{j}:{v:.6f}" for j, v in enumerate(row))
            f.write(f"{label:g} {feats}\n")


@pytest.fixture()
def svm_data(tmp_path):
    rng = np.random.RandomState(7)
    X = rng.rand(400, 6).astype(np.float32)
    y = ((X[:, 0] + X[:, 1] > 1.0)).astype(np.float32)
    train = tmp_path / "train.svm"
    test = tmp_path / "test.svm"
    _write_libsvm(train, X[:300], y[:300])
    _write_libsvm(test, X[300:], y[300:])
    return tmp_path, str(train), str(test), y[300:]


def _conf(tmp_path, train, test, **kw):
    lines = {
        "task": "train", "booster": "gbtree",
        "objective": "binary:logistic", "eta": "0.5", "max_depth": "3",
        "num_round": "5", "data": train, "eval[test]": test,
        "model_out": str(tmp_path / "final.model"), "silent": "1",
    }
    lines.update(kw)
    conf = tmp_path / "run.conf"
    conf.write_text("".join(f"{k} = {v}\n" for k, v in lines.items()))
    return str(conf)


def test_cli_train_pred_eval_dump(svm_data, tmp_path, capsys):
    tp, train, test, y_test = svm_data
    conf = _conf(tp, train, test)
    assert cli_main([conf]) == 0
    model = str(tp / "final.model")
    assert os.path.exists(model)

    pred_file = str(tp / "pred.txt")
    assert cli_main([conf, "task=pred", f"test:data={test}",
                     f"model_in={model}", f"name_pred={pred_file}"]) == 0
    preds = np.loadtxt(pred_file)
    assert preds.shape == (100,)
    acc = ((preds > 0.5) == (y_test > 0.5)).mean()
    assert acc > 0.9

    dump_file = str(tp / "dump.txt")
    assert cli_main([conf, "task=dump", f"model_in={model}",
                     f"name_dump={dump_file}"]) == 0
    text = open(dump_file).read()
    assert "booster[0]" in text and "leaf=" in text

    assert cli_main([conf, "task=eval", f"model_in={model}"]) == 0


def test_cli_continue_training(svm_data, tmp_path):
    tp, train, test, _ = svm_data
    m1 = str(tp / "m1.model")
    conf = _conf(tp, train, test, num_round="3", model_out=m1)
    assert cli_main([conf]) == 0
    m2 = str(tp / "m2.model")
    assert cli_main([conf, f"model_in={m1}", f"model_out={m2}",
                     "num_round=2"]) == 0
    import xgboost_tpu as xgb
    bst = xgb.Booster(model_file=m2)
    assert bst.gbtree.num_trees == 5


def test_cli_save_period_and_checkpoint_resume(svm_data, tmp_path):
    tp, train, test, _ = svm_data
    ckpt = str(tp / "ckpt")
    conf = _conf(tp, train, test, num_round="4", save_period="2",
                 model_dir=str(tp), checkpoint_dir=ckpt)
    assert cli_main([conf]) == 0
    assert os.path.exists(tp / "0002.model")
    assert os.path.exists(tp / "0004.model")
    # newest two checkpoints kept; checkpoints land at fused segment
    # boundaries (boundary_align=save_period=2 -> rounds 2 and 4)
    # the persistent jit cache lives alongside the ring (RECOVERY.md)
    kept = sorted(f for f in os.listdir(ckpt) if f.startswith("ckpt-"))
    assert kept == ["ckpt-000002.model", "ckpt-000004.model"]

    # "kill" after round 4 of 6: rerun with num_round=6 resumes from ckpt 4
    conf6 = _conf(tp, train, test, num_round="6", checkpoint_dir=ckpt,
                  model_out=str(tp / "resumed.model"))
    assert cli_main([conf6]) == 0
    import xgboost_tpu as xgb
    bst = xgb.Booster(model_file=str(tp / "resumed.model"))
    assert bst.gbtree.num_trees == 6


def test_stdin_data_loading(monkeypatch):
    """data=stdin (reference io.cpp:32-38, the Hadoop-streaming channel)."""
    import io as _io
    import sys

    import xgboost_tpu as xgb

    text = b"1 0:0.5 2:1.0\n0 1:0.25\n1 0:0.9\n"
    monkeypatch.setattr(sys, "stdin",
                        type("S", (), {"buffer": _io.BytesIO(text)})())
    d = xgb.DMatrix("stdin")
    assert d.num_row == 3 and d.num_col == 3
    np.testing.assert_array_equal(d.get_label(), [1, 0, 1])


def test_cli_fused_no_evals_matches_evald_run(svm_data):
    """With no eval sets / save_period / checkpoints, task_train fuses
    the round loop into one launch; the model must equal the eval'd
    (per-round) run's bitwise."""
    tp, train, test, _ = svm_data
    import xgboost_tpu as xgb
    conf_seq = _conf(tp, train, test,
                     model_out=str(tp / "seq.model"))
    assert cli_main([str(conf_seq)]) == 0
    from pathlib import Path
    conf_fused = Path(_conf(tp, train, test,
                            model_out=str(tp / "fused.model")))
    # drop the eval set -> fused eligibility
    text = conf_fused.read_text().replace(f"eval[test] = {test}\n", "")
    conf_fused.write_text(text)
    assert cli_main([str(conf_fused)]) == 0
    b1 = xgb.Booster(model_file=str(tp / "seq.model"))
    b2 = xgb.Booster(model_file=str(tp / "fused.model"))
    s1, s2 = b1.gbtree.get_state(), b2.gbtree.get_state()
    for k in s1:
        np.testing.assert_array_equal(s1[k], s2[k], err_msg=k)

/*
 * C-host smoke driver for the xgboost_tpu C ABI: train agaricus from a
 * non-Python host, eval, predict, save/load round-trip, dump.
 * Mirrors the reference's basic walkthrough through its C wrapper.
 * Usage: capi_demo <train.libsvm> <test.libsvm> <model_out>
 */
#include <stdio.h>
#include <string.h>

#include "xgboost_tpu_capi.h"

int main(int argc, char **argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s train test model_out\n", argv[0]);
    return 2;
  }
  void *dtrain = XGDMatrixCreateFromFile(argv[1], 1);
  void *dtest = XGDMatrixCreateFromFile(argv[2], 1);
  printf("rows train=%lu test=%lu\n", XGDMatrixNumRow(dtrain),
         XGDMatrixNumRow(dtest));

  void *dmats[2] = {dtrain, dtest};
  void *bst = XGBoosterCreate(dmats, 2);
  XGBoosterSetParam(bst, "objective", "binary:logistic");
  XGBoosterSetParam(bst, "max_depth", "3");
  XGBoosterSetParam(bst, "eta", "1.0");

  const char *names[2] = {"train", "test"};
  for (int i = 0; i < 2; ++i) {
    XGBoosterUpdateOneIter(bst, i, dtrain);
    printf("%s\n", XGBoosterEvalOneIter(bst, i, dmats, names, 2));
  }

  xgt_ulong n = 0;
  const float *preds = XGBoosterPredict(bst, dtest, 0, 0, &n);
  printf("npred=%lu pred0=%.6f\n", n, preds[0]);

  XGBoosterSaveModel(bst, argv[3]);
  void *bst2 = XGBoosterCreate(dmats, 0);
  XGBoosterLoadModel(bst2, argv[3]);
  xgt_ulong n2 = 0;
  const float *p2 = XGBoosterPredict(bst2, dtest, 0, 0, &n2);
  int same = (n == n2);
  for (xgt_ulong i = 0; same && i < n; ++i) same = (preds[i] == p2[i]);
  /* note: preds ptr was invalidated by the second Predict on bst2?  No:
   * anchors are per-handle, bst != bst2, so both stay valid. */
  printf("roundtrip=%s\n", same ? "identical" : "MISMATCH");

  xgt_ulong ntree = 0;
  const char **dump = XGBoosterDumpModel(bst, "", 0, &ntree);
  printf("dump trees=%lu first_node_ok=%d\n", ntree,
         dump[0] != NULL && strstr(dump[0], "0:[") != NULL);

  xgt_ulong rawlen = 0;
  XGBoosterGetModelRaw(bst, &rawlen);
  printf("rawlen=%lu\n", rawlen);

  XGBoosterFree(bst2);
  XGBoosterFree(bst);
  XGDMatrixFree(dtest);
  XGDMatrixFree(dtrain);
  printf("C-ABI-OK\n");
  return 0;
}

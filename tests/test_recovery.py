"""Fault injection + recovery equivalence.

The reference bar: ``AllreduceMock`` kills a worker at an exact
(version, seqno, ntrial) coordinate, the keepalive wrapper restarts it,
and recovery must reproduce bit-identical results
(``subtree/rabit/src/allreduce_mock.h:37-44``,
``tracker/rabit_demo.py:26-40``, ``test/local_recover.cc:30-60``).

Here: a boosting round is a version, each tree-growth launch a seqno;
the CLI's keepalive mode restarts training from the checkpoint ring and
the final model must be bit-identical to an uninterrupted run.
"""

import os

import numpy as np
import pytest

from xgboost_tpu.parallel.mock import (FaultInjector, WorkerFailure,
                                       clear_fault_injection,
                                       set_fault_injection)


def _write_libsvm(path, n=400, f=6, n_class=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] * 3 + X[:, 1]).astype(int) % n_class
    with open(path, "w") as fh:
        for i in range(n):
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(f)
                             if X[i, j] > 0.15)  # some sparsity
            fh.write(f"{y[i]} {feats}\n")


def test_injector_coordinates():
    inj = FaultInjector([(2, 1, 0)], trial=0)
    inj.begin_round(0)
    inj.collective(); inj.collective()
    inj.begin_round(2)
    inj.collective()                      # seqno 0: no fire
    with pytest.raises(WorkerFailure):
        inj.collective()                  # version 2, seqno 1 -> dies
    # restarted process (trial 1) sails past the same coordinate
    inj2 = FaultInjector([(2, 1, 0)], trial=1)
    inj2.begin_round(2)
    inj2.collective(); inj2.collective()


def test_injection_fires_in_boosting_loop(tmp_path):
    import xgboost_tpu as xgb
    rng = np.random.RandomState(0)
    X = rng.rand(200, 4).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    set_fault_injection([(1, 0, 0)], trial=0)
    try:
        bst = xgb.Booster({"objective": "binary:logistic", "max_depth": 2},
                          cache=[xgb.DMatrix(X, label=y)])
        d = xgb.DMatrix(X, label=y)
        bst.update(d, 0)                  # version 0: fine
        with pytest.raises(WorkerFailure):
            bst.update(d, 1)              # version 1, seqno 0 -> dies
    finally:
        clear_fault_injection()


def _run_cli(args):
    from xgboost_tpu.cli import main
    rc = main(args)
    assert rc == 0


def _model_state(path):
    import xgboost_tpu as xgb
    bst = xgb.Booster(model_file=str(path))
    return bst.gbtree.get_state()


@pytest.mark.parametrize("mock_spec,n_deaths", [
    ("0,3,1,0", 1),            # die mid-round (between class trees)
    ("0,2,0,0;0,4,2,1", 2),    # die twice, the second after one restart
])
def test_kill_restart_bit_identical(tmp_path, capfd, mock_spec, n_deaths):
    """Train -> injected death -> keepalive restart from checkpoint ->
    final model bit-identical to an uninterrupted run."""
    data = tmp_path / "train.libsvm"
    _write_libsvm(str(data))
    common = [f"data={data}", "task=train", "num_round=6", "silent=2",
              "objective=multi:softmax", "num_class=3", "max_depth=3",
              "eta=0.5", "max_bin=16", "save_period=0"]

    m_ref = tmp_path / "ref.model"
    _run_cli(common + [f"model_out={m_ref}",
                       f"checkpoint_dir={tmp_path / 'ck_ref'}"])

    capfd.readouterr()
    m_mock = tmp_path / "mock.model"
    _run_cli(common + [f"model_out={m_mock}",
                       f"checkpoint_dir={tmp_path / 'ck_mock'}",
                       f"mock={mock_spec}", "keepalive=1"])
    # the injected deaths must actually fire (not a vacuous pass)
    err = capfd.readouterr().err
    assert err.count("[mock]") == n_deaths, err

    ref, got = _model_state(m_ref), _model_state(m_mock)
    assert set(ref) == set(got)
    for k in ref:
        np.testing.assert_array_equal(ref[k], got[k], err_msg=k)


def test_kill_without_keepalive_raises(tmp_path):
    data = tmp_path / "train.libsvm"
    _write_libsvm(str(data))
    from xgboost_tpu.cli import main
    with pytest.raises(WorkerFailure):
        main([f"data={data}", "task=train", "num_round=3", "silent=2",
              "objective=multi:softmax", "num_class=3", "max_bin=16",
              f"model_out={tmp_path / 'm.model'}", "mock=0,1,0,0"])

"""Gang-batched tenant lanes (xgboost_tpu.pipeline.lanes, PIPELINE.md
"Gang-batched lanes").

Acceptance criteria covered here:
(a) BIT-identity: a stacked lane's published model bytes equal its solo
    host-loop run's, byte for byte — including pad lanes (N=3 in a
    width-4 stack), N=64, mixed shape buckets, and the steady-bucket
    carry fast path;
(b) dispatch economics: stacked segment dispatches per cycle are
    INDEPENDENT of lane count within a bucket (the tentpole claim), and
    the pad/stacked accounting matches the bucket arithmetic;
(c) isolation: a gate-failing or crashing lane never poisons its
    neighbors' bytes or status;
(d) the ``XGBTPU_LANE_STACK=0`` kill switch routes through the host
    loop (zero stacked dispatches) and still produces the same bytes;
(e) steady-state compile budget: re-running an already-warm bucket
    shape compiles NOTHING (recompile_guard, ANALYSIS.md XGT001).
"""

import os
import threading

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.obs import lane_metrics
from xgboost_tpu.pipeline import (DataSource, SyntheticDataSource,
                                  run_tenant_lanes)
from xgboost_tpu.pipeline.lanes import LaneGang, _Arrival, _bucket_of

PARAMS = {"objective": "binary:logistic", "max_depth": 2, "eta": 0.3,
          "silent": 1}


def lane_kwargs(tmp_path, mode, name, seed, cycles=2, rounds=2,
                n_features=4, params=None, **kw):
    d = tmp_path / mode / name
    base = {
        "publish_path": str(d / "model.bin"),
        "workdir": str(d / "work"),
        "source": SyntheticDataSource(n_rows=64, n_features=n_features,
                                      seed=seed),
        "rounds_per_cycle": rounds, "cycles": cycles,
        "params": dict(params or PARAMS),
    }
    base.update(kw)
    return base


def make_lanes(tmp_path, mode, n, **kw):
    return {f"tenant{i:03d}": lane_kwargs(tmp_path, mode,
                                          f"tenant{i:03d}", 100 + i,
                                          **kw)
            for i in range(n)}


def model_bytes(kwargs):
    with open(kwargs["publish_path"], "rb") as f:
        return f.read()


def lane_counts():
    lm = lane_metrics()
    return {"dispatches": lm.dispatches.value,
            "stacked": lm.stacked.value, "padded": lm.padded.value,
            "restacks": lm.restacks.value}


def counts_delta(before):
    after = lane_counts()
    return {k: after[k] - before[k] for k in before}


def assert_all_ok(results):
    for name, r in results.items():
        assert r["status"] == "ok", (name, r)


def _pow2(n):
    p = 1
    while p < n:
        p *= 2
    return p


# ------------------------------------------------------------- bucket key
def test_bucket_of_groups_by_shape_and_pads_rows():
    import jax.numpy as jnp
    from types import SimpleNamespace

    def spec(n_rows=100, n_features=4, w=7, subsample=1.0, K=1):
        return SimpleNamespace(
            n_rows=n_rows, n_features=n_features, subsample=subsample,
            binned=jnp.zeros((n_rows, n_features), jnp.int8),
            cut_values=jnp.zeros((n_features, w), jnp.float32),
            K=K, npar=1, n_rounds=2, seg_k=64, cfg="cfg",
            split_finder=None, grad_fn="g", pred_chunk=0)

    # rows pad to a shared power-of-two bucket when subsample == 1
    assert _bucket_of(spec(n_rows=65)) == _bucket_of(spec(n_rows=100))
    assert _bucket_of(spec(n_rows=64)) != _bucket_of(spec(n_rows=100))
    # subsample < 1 draws are N-shaped: exact rows only
    assert (_bucket_of(spec(n_rows=65, subsample=0.5))
            != _bucket_of(spec(n_rows=100, subsample=0.5)))
    assert (_bucket_of(spec(n_rows=100, subsample=0.5))
            == _bucket_of(spec(n_rows=100, subsample=0.5)))
    # cut width pads to a power of two (floor 8); features never pad
    assert _bucket_of(spec(w=5)) == _bucket_of(spec(w=8))
    assert _bucket_of(spec(n_features=5)) != _bucket_of(spec())
    assert _bucket_of(spec(K=2)) != _bucket_of(spec())


# ----------------------------------------------------------- bit-identity
@pytest.mark.parametrize("n", [2, 3, 8])
def test_stacked_bit_identity(tmp_path, n):
    """Stacked bytes == solo bytes per tenant; dispatch count per cycle
    is independent of lane count; pad accounting matches pow2 width."""
    cycles = 2
    stacked = make_lanes(tmp_path, "stacked", n, cycles=cycles)
    before = lane_counts()
    res_s = run_tenant_lanes(stacked, quiet=True, stacked=True,
                             window_sec=1.0)
    delta = counts_delta(before)
    solo = make_lanes(tmp_path, "solo", n, cycles=cycles)
    res_h = run_tenant_lanes(solo, quiet=True, stacked=False)
    assert_all_ok(res_s)
    assert_all_ok(res_h)
    for name in stacked:
        assert model_bytes(stacked[name]) == model_bytes(solo[name]), \
            f"{name}: stacked bytes != solo bytes"
    # ONE stacked dispatch per cycle regardless of n (tentpole claim)
    assert delta["dispatches"] == cycles
    assert delta["stacked"] == n * cycles
    assert delta["padded"] == (_pow2(n) - n) * cycles


def test_stacked_bit_identity_n64(tmp_path):
    cycles = 1
    stacked = make_lanes(tmp_path, "stacked", 64, cycles=cycles)
    before = lane_counts()
    res_s = run_tenant_lanes(stacked, quiet=True, stacked=True,
                             window_sec=2.0)
    delta = counts_delta(before)
    solo = make_lanes(tmp_path, "solo", 64, cycles=cycles)
    res_h = run_tenant_lanes(solo, quiet=True, stacked=False)
    assert_all_ok(res_s)
    assert_all_ok(res_h)
    mismatched = [name for name in stacked
                  if model_bytes(stacked[name]) != model_bytes(solo[name])]
    assert not mismatched, f"bytes diverged for {mismatched}"
    assert delta["dispatches"] == cycles  # width-64, still one per cycle
    assert delta["stacked"] == 64 * cycles
    assert delta["padded"] == 0


def test_mixed_shape_buckets(tmp_path):
    """Lanes with different feature counts form separate buckets that
    dispatch independently — and stay bit-identical to solo."""
    lanes_s = make_lanes(tmp_path, "stacked", 2, cycles=1)
    lanes_s.update({f"wide{i}": lane_kwargs(tmp_path, "stacked",
                                            f"wide{i}", 200 + i,
                                            cycles=1, n_features=7)
                    for i in range(2)})
    before = lane_counts()
    res_s = run_tenant_lanes(lanes_s, quiet=True, stacked=True,
                             window_sec=1.0)
    delta = counts_delta(before)
    lanes_h = make_lanes(tmp_path, "solo", 2, cycles=1)
    lanes_h.update({f"wide{i}": lane_kwargs(tmp_path, "solo",
                                            f"wide{i}", 200 + i,
                                            cycles=1, n_features=7)
                    for i in range(2)})
    res_h = run_tenant_lanes(lanes_h, quiet=True, stacked=False)
    assert_all_ok(res_s)
    assert_all_ok(res_h)
    for name in lanes_s:
        assert model_bytes(lanes_s[name]) == model_bytes(lanes_h[name])
    assert lane_metrics().buckets.value == 2.0
    assert delta["dispatches"] == 2  # one per bucket
    assert delta["stacked"] == 4
    assert delta["padded"] == 0


# --------------------------------------------------------------- isolation
def test_gate_fail_isolated_from_neighbors(tmp_path):
    """A lane that can never clear its gate keeps publishing nothing
    after cycle 0 — its bucket peers' bytes are untouched."""
    lanes_s = make_lanes(tmp_path, "stacked", 2, cycles=2)
    lanes_s["picky"] = lane_kwargs(tmp_path, "stacked", "picky", 999,
                                   cycles=2, min_delta=1e9)
    res_s = run_tenant_lanes(lanes_s, quiet=True, stacked=True,
                             window_sec=1.0)
    lanes_h = make_lanes(tmp_path, "solo", 2, cycles=2)
    res_h = run_tenant_lanes(lanes_h, quiet=True, stacked=False)
    assert_all_ok(res_s)
    assert_all_ok(res_h)
    assert res_s["picky"]["summary"]["gate_failed"] >= 1
    for name in lanes_h:
        assert model_bytes(lanes_s[name]) == model_bytes(lanes_h[name])


class _CrashOnCycle(DataSource):
    """Healthy synthetic cycles except one poisoned cycle index."""

    def __init__(self, crash_cycle, seed):
        self.crash_cycle = crash_cycle
        self.inner = SyntheticDataSource(n_rows=64, n_features=4,
                                         seed=seed)

    def next_cycle(self, cycle):
        if cycle == self.crash_cycle:
            raise RuntimeError("poisoned source cycle")
        return self.inner.next_cycle(cycle)


def test_crashing_lane_isolated_from_neighbors(tmp_path):
    """One lane's source raising mid-run is contained in that lane's
    error count; neighbors' bytes stay bit-identical to solo."""
    lanes_s = make_lanes(tmp_path, "stacked", 2, cycles=2)
    lanes_s["crashy"] = lane_kwargs(
        tmp_path, "stacked", "crashy", 999, cycles=2,
        source=_CrashOnCycle(crash_cycle=1, seed=999))
    res_s = run_tenant_lanes(lanes_s, quiet=True, stacked=True,
                             window_sec=0.2)
    lanes_h = make_lanes(tmp_path, "solo", 2, cycles=2)
    res_h = run_tenant_lanes(lanes_h, quiet=True, stacked=False)
    assert_all_ok(res_h)
    # the trainer contains per-cycle errors: status ok, errors counted
    assert res_s["crashy"]["status"] == "ok"
    assert res_s["crashy"]["summary"]["errors"] >= 1
    for name in lanes_h:
        assert res_s[name]["status"] == "ok"
        assert model_bytes(lanes_s[name]) == model_bytes(lanes_h[name])


# ------------------------------------------------------------- kill switch
def test_lane_stack_env_kill_switch(tmp_path, monkeypatch):
    """XGBTPU_LANE_STACK=0 routes run_tenant_lanes through the host
    loop: zero stacked dispatches, same bytes."""
    monkeypatch.setenv("XGBTPU_LANE_STACK", "0")
    lanes_off = make_lanes(tmp_path, "env_off", 2, cycles=1)
    before = lane_counts()
    res_off = run_tenant_lanes(lanes_off, quiet=True)
    assert counts_delta(before)["dispatches"] == 0
    monkeypatch.setenv("XGBTPU_LANE_STACK", "1")
    lanes_on = make_lanes(tmp_path, "env_on", 2, cycles=1)
    before = lane_counts()
    res_on = run_tenant_lanes(lanes_on, quiet=True, window_sec=1.0)
    assert counts_delta(before)["dispatches"] == 1
    assert_all_ok(res_off)
    assert_all_ok(res_on)
    for name in lanes_on:
        assert model_bytes(lanes_on[name]) == model_bytes(lanes_off[name])


# ------------------------------------------------- steady-bucket carry path
def test_carry_fast_path_reuses_stack_and_stays_identical():
    """Long-lived boosters re-dispatching the same bucket hit the carry
    (no re-stack after the first dispatch) and the bytes still match a
    round-for-round solo run."""
    def boosters(tag):
        out = []
        for i in range(4):
            rng = np.random.RandomState(300 + i)
            X = rng.rand(64, 4).astype(np.float32)
            y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
            d = xgb.DMatrix(X, label=y)
            b = xgb.Booster(dict(PARAMS, seed=300 + i), [d])
            out.append((b, d))
        return out

    gang = LaneGang(expected=0)
    stacked = boosters("stacked")
    before = lane_counts()
    for cycle in range(3):
        arrs = []
        for i, (b, d) in enumerate(stacked):
            spec, why = b.fused_lane_spec(d, cycle * 2, 2)
            assert spec is not None, why
            arrs.append(_Arrival(f"lane{i}", spec, lambda it: None))
        gang._dispatch_bucket(_bucket_of(arrs[0].spec), arrs)
        for a in arrs:
            assert a.exc is None
    delta = counts_delta(before)
    assert delta["dispatches"] == 3
    assert delta["restacks"] == 1  # cycles 2..3 rode the carry

    solo = boosters("solo")
    for cycle in range(3):
        for b, d in solo:
            b.update_many(d, cycle * 2, 2)
    for (bs, _), (bh, _) in zip(stacked, solo):
        assert bs.save_raw() == bh.save_raw()


def test_steady_bucket_recompiles_nothing(tmp_path, recompile_guard):
    """A second run over an already-warm bucket shape stays inside the
    jit caches end to end (ANALYSIS.md XGT001)."""
    warm = make_lanes(tmp_path, "warm", 2, cycles=1)
    assert_all_ok(run_tenant_lanes(warm, quiet=True, stacked=True,
                                   window_sec=1.0))
    again = make_lanes(tmp_path, "again", 2, cycles=1)
    with recompile_guard.expect(0):
        assert_all_ok(run_tenant_lanes(again, quiet=True, stacked=True,
                                       window_sec=1.0))


# --------------------------------------------------------- host-loop bound
class _TrackingSource(DataSource):
    """Counts concurrently-active next_cycle calls across instances."""
    lock = threading.Lock()
    cur = 0
    peak = 0

    def __init__(self, seed):
        self.inner = SyntheticDataSource(n_rows=64, n_features=4,
                                         seed=seed)

    def next_cycle(self, cycle):
        cls = _TrackingSource
        with cls.lock:
            cls.cur += 1
            cls.peak = max(cls.peak, cls.cur)
        try:
            import time
            time.sleep(0.05)
            return self.inner.next_cycle(cycle)
        finally:
            with cls.lock:
                cls.cur -= 1


def test_host_loop_bounds_workers(tmp_path):
    _TrackingSource.cur = _TrackingSource.peak = 0
    lanes = {f"t{i}": lane_kwargs(tmp_path, "bound", f"t{i}", 400 + i,
                                  cycles=1,
                                  source=_TrackingSource(400 + i))
             for i in range(6)}
    res = run_tenant_lanes(lanes, quiet=True, stacked=False,
                           max_workers=2)
    assert_all_ok(res)
    assert 1 <= _TrackingSource.peak <= 2


# ------------------------------------------------------------ lane seeding
def test_lane_name_derives_seed(tmp_path):
    """Two tenants with identical data/params but different names grow
    different models (per-lane seed from the NAME); an explicit seed
    param pins them back together."""
    def pair(mode, params):
        return {name: lane_kwargs(tmp_path, mode, name, 7, cycles=1,
                                  params=params)
                for name in ("alpha", "beta")}

    lanes = pair("named", PARAMS)
    assert_all_ok(run_tenant_lanes(lanes, quiet=True, stacked=False))
    assert model_bytes(lanes["alpha"]) != model_bytes(lanes["beta"])

    pinned = pair("pinned", dict(PARAMS, seed=5))
    assert_all_ok(run_tenant_lanes(pinned, quiet=True, stacked=False))
    assert model_bytes(pinned["alpha"]) == model_bytes(pinned["beta"])

"""Chaos suite: crash-safe persistence + failure-path hardening
(xgboost_tpu.reliability; design in RELIABILITY.md).

Acceptance criteria covered here:
(a) a kill at any injected fault point during save_model/checkpointing
    never yields a silently-wrong model — the torn file fails
    verification, the checkpoint ring falls back to the older replica,
    and resumed training finishes BIT-identical to an uninterrupted
    run;
(b) overwriting the served model file with corrupt bytes under
    concurrent traffic causes zero failed predictions, exactly one
    engine build attempt until the file changes, and a /healthz that
    reports the reload error;
(c) SIGTERM drain finishes in-flight requests and 503s new ones;
(d) abandoned requests are shed before device dispatch.

Every fault is injected through reliability/faults.py seams inside the
REAL write/read/reload code paths — no test doubles.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.profiling import ServingMetrics, reliability_metrics
from xgboost_tpu.reliability import faults
from xgboost_tpu.reliability.integrity import (ModelIntegrityError,
                                               add_footer, atomic_write,
                                               has_footer, quarantine,
                                               verify_model_bytes)
from xgboost_tpu.serving import MicroBatcher, ModelRegistry, run_server


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    """Every test starts and ends with a disarmed fault registry."""
    faults.clear_faults()
    yield
    faults.clear_faults()


def _train(seed=0, rounds=4, **params):
    rng = np.random.RandomState(seed)
    X = rng.rand(200, 5).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    p = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.4,
         "silent": 1, "seed": seed, **params}
    return xgb.train(p, xgb.DMatrix(X, label=y), rounds), X


@pytest.fixture(scope="module")
def model(tmp_path_factory):
    bst, X = _train()
    path = str(tmp_path_factory.mktemp("reliability") / "m.model")
    bst.save_model(path)
    return bst, X, path


# ----------------------------------------------------------- atomic_write
def test_atomic_write_basic(tmp_path):
    p = tmp_path / "f.bin"
    atomic_write(str(p), b"hello")
    assert p.read_bytes() == b"hello"
    atomic_write(str(p), b"world")  # overwrites atomically
    assert p.read_bytes() == b"world"
    # no tmp droppings
    assert os.listdir(tmp_path) == ["f.bin"]


def test_atomic_write_preserves_file_mode(tmp_path):
    """mkstemp's private 0600 must not leak to the destination: fresh
    files honor the umask (like plain open did) and overwrites keep
    the file's existing mode."""
    p = tmp_path / "f.bin"
    old = os.umask(0o022)
    try:
        atomic_write(str(p), b"fresh")
        assert os.stat(p).st_mode & 0o777 == 0o644
    finally:
        os.umask(old)
    os.chmod(p, 0o600)  # operator tightened it; overwrite keeps it
    atomic_write(str(p), b"overwrite")
    assert os.stat(p).st_mode & 0o777 == 0o600


def test_atomic_write_failure_keeps_old_content(tmp_path):
    p = tmp_path / "f.bin"
    atomic_write(str(p), b"precious")
    faults.inject("enospc", path_sub="f.bin")
    with pytest.raises(OSError):
        atomic_write(str(p), b"replacement")
    # destination untouched, tmp file cleaned up
    assert p.read_bytes() == b"precious"
    assert os.listdir(tmp_path) == ["f.bin"]


# ------------------------------------------------------- footer/verify
def test_footer_roundtrip_and_detection():
    payload = b"some model bytes" * 100
    raw = add_footer(payload)
    assert has_footer(raw)
    assert verify_model_bytes(raw) == payload
    # bit flip anywhere in the payload is caught
    flipped = bytearray(raw)
    flipped[37] ^= 0x10
    with pytest.raises(ModelIntegrityError, match="CRC32 mismatch"):
        verify_model_bytes(bytes(flipped))
    # flip inside the footer itself is caught too (crc won't match)
    flipped2 = bytearray(raw)
    flipped2[-5] = ord("0") if flipped2[-5] != ord("0") else ord("1")
    with pytest.raises(ModelIntegrityError):
        verify_model_bytes(bytes(flipped2))


def test_torn_write_detected_at_several_offsets(model, tmp_path):
    """Satellite: torn-write detection across the whole file — every
    truncation point inside the payload OR the footer raises the typed
    error (never a silently-wrong model, never a crash in np.load
    without the integrity type)."""
    _, _, path = model
    raw = open(path, "rb").read()
    n = len(raw)
    for cut in (8, 100, n // 4, n // 2, (9 * n) // 10, n - 30, n - 5, n - 1):
        torn = tmp_path / f"torn_{cut}.model"
        torn.write_bytes(raw[:cut])
        with pytest.raises(ModelIntegrityError):
            xgb.Booster(model_file=str(torn))


def test_footerless_legacy_file_loads_with_warning(model, tmp_path, capfd):
    bst, X, path = model
    raw = open(path, "rb").read()
    legacy = tmp_path / "legacy.model"
    legacy.write_bytes(verify_model_bytes(raw))  # strip the footer
    capfd.readouterr()
    b2 = xgb.Booster(model_file=str(legacy))
    assert "[integrity]" in capfd.readouterr().err
    ref = bst.predict(xgb.DMatrix(X[:10]))
    assert np.array_equal(b2.predict(xgb.DMatrix(X[:10])), ref)


def test_bs64_model_has_footer_and_detects_corruption(model, tmp_path):
    bst, X, path = model
    p = str(tmp_path / "m.b64")
    bst.save_model(p, save_base64=True)
    raw = open(p, "rb").read()
    assert raw.startswith(b"bs64\t") and has_footer(raw)
    assert np.array_equal(xgb.Booster(model_file=p).predict(
        xgb.DMatrix(X[:5])), bst.predict(xgb.DMatrix(X[:5])))
    bad = bytearray(raw)
    bad[20] ^= 0x04
    (tmp_path / "bad.b64").write_bytes(bytes(bad))
    with pytest.raises(ModelIntegrityError):
        xgb.Booster(model_file=str(tmp_path / "bad.b64"))


def test_quarantine_moves_file_aside(tmp_path):
    p = tmp_path / "x.model"
    p.write_bytes(b"junk")
    q = quarantine(str(p))
    assert not p.exists() and q.endswith(".corrupt")
    # a second quarantine of the same name numbers itself
    p.write_bytes(b"junk2")
    q2 = quarantine(str(p))
    assert q2 != q and os.path.exists(q2)


def test_quarantine_fsyncs_parent_dir(tmp_path, monkeypatch):
    """ISSUE 17 satellite: the quarantine rename must be made DURABLE
    (directory fsync) — a crash right after quarantining a corrupt
    ring member must not resurrect it into the ring on reboot."""
    fsynced = []
    real = os.fsync
    monkeypatch.setattr(
        os, "fsync", lambda fd: (fsynced.append(fd), real(fd))[1])
    p = tmp_path / "z.model"
    p.write_bytes(b"junk")
    q = quarantine(str(p))
    assert not p.exists() and os.path.exists(q)
    assert fsynced, "quarantine rename was not fsynced"


# ------------------------------------------------- fault registry itself
def test_fault_spec_errors_fail_loud_and_arm_nothing(tmp_path):
    """ISSUE 17 satellite: every malformed spec raises the typed
    FaultSpecError at ARM time, emits a ``faults.invalid_spec`` obs
    event, and arms NOTHING — including when the bad entry TRAILS
    valid ones (two-phase parse), so a chaos run with a typo'd spec
    dies at startup instead of passing with untested faults."""
    from xgboost_tpu.obs import events
    log = str(tmp_path / "obs.jsonl")
    events.configure_log(log)
    bad_specs = (
        "bogus_kind@ckpt",          # unknown kind
        "torn_write=abc@ckpt",      # non-numeric arg
        "torn_write=128@ckpt*0",    # times < 1
        "bit_flip@ckpt*zz",         # non-integer times
        "=3@x",                     # empty kind
        "   ;  ;",                  # spec arms nothing
        "torn_write=128@ckpt;bogus@x",  # trailing typo: NOTHING armed
    )
    try:
        for bad in bad_specs:
            with pytest.raises(faults.FaultSpecError):
                faults.install_spec(bad)
            assert not faults.active(), bad
    finally:
        events.configure_log(None)
    recs = [json.loads(line) for line in open(log)]
    names = [r["name"] for r in recs if r.get("kind") == "event"]
    assert names.count("faults.invalid_spec") == len(bad_specs)


def test_gang_fault_kinds_fire_at_coordinate():
    """The gang seam: ``host_loss``/``partition`` arm from a spec and
    fire exactly at their ``t<trial>.r<rank>.v<version>.`` coordinate,
    once each."""
    faults.install_spec("host_loss@t0.r0.v2.;partition=3.5@t0.r1.v4.")
    assert faults.gang_fault("t0.r0.v1.") == []
    assert faults.gang_fault("t1.r0.v2.") == []  # other trial: no fire
    assert faults.gang_fault("t0.r0.v2.") == [("host_loss", None)]
    assert faults.gang_fault("t0.r0.v2.") == []  # fired once, disarmed
    assert faults.gang_fault("t0.r1.v4.") == [("partition", 3.5)]


def test_fault_spec_parsing():
    faults.install_spec("torn_write=128@ckpt-000003;slow_read=0.01#3;enospc")
    assert faults.active()
    faults.clear_faults()
    assert not faults.active()
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.inject("meteor_strike")


def test_fault_spec_star_times_survives_config_comments(tmp_path):
    """`*times` is the config-file-safe multiplier: `#` would be
    stripped as a comment by parse_config_file."""
    from xgboost_tpu.config import parse_config_file
    cfg = tmp_path / "chaos.conf"
    cfg.write_text("faults = slow_read=0.001@probe*3\n")
    pairs = dict(parse_config_file(str(cfg)))
    faults.install_spec(pairs["faults"])
    p = str(tmp_path / "probe.bin")
    open(p, "wb").write(b"x")
    from xgboost_tpu.reliability.integrity import read_file
    n0 = faults.fired("slow_read")
    for _ in range(4):
        read_file(p)
    assert faults.fired("slow_read") - n0 == 3  # armed 3 times, not 1


def test_bit_flip_on_empty_payload_is_noop(tmp_path):
    """Chaos on a zero-byte file must not crash the injector itself."""
    faults.inject("read_flip", 5, path_sub="empty")
    p = str(tmp_path / "empty.bin")
    open(p, "wb").write(b"")
    from xgboost_tpu.reliability.integrity import read_file
    assert read_file(p) == b""


def test_injected_faults_fire_once_and_count(tmp_path):
    p = str(tmp_path / "f.bin")
    n0 = faults.fired("torn_write")
    faults.inject("torn_write", 3, path_sub="f.bin", times=1)
    atomic_write(p, b"0123456789")
    assert open(p, "rb").read() == b"012"          # torn at byte 3
    atomic_write(p, b"0123456789")                 # disarmed: full write
    assert open(p, "rb").read() == b"0123456789"
    assert faults.fired("torn_write") - n0 == 1
    assert reliability_metrics().faults_injected.value >= 1


def test_path_filter_scopes_faults(tmp_path):
    faults.inject("bit_flip", 0, path_sub="target")
    other = str(tmp_path / "other.bin")
    atomic_write(other, b"AAAA")
    assert open(other, "rb").read() == b"AAAA"     # filter did not match
    target = str(tmp_path / "target.bin")
    atomic_write(target, b"AAAA")
    assert open(target, "rb").read() != b"AAAA"    # flipped


# ------------------------------------------------- save_model hardening
def test_save_model_is_atomic_under_enospc(model, tmp_path):
    """Satellite: a failed save never tears the destination — the old
    (watched!) model file survives byte-identical."""
    bst, _, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    before = open(p, "rb").read()
    faults.inject("enospc", path_sub="m.model")
    with pytest.raises(OSError):
        bst.save_model(p)
    assert open(p, "rb").read() == before
    # and the file still verifies + loads
    xgb.Booster(model_file=p)


def test_torn_final_model_write_is_never_silently_wrong(tmp_path):
    """Acceptance (a), model_out leg: a torn write of the FINAL model
    fails verification on load instead of producing wrong predictions."""
    data = tmp_path / "train.libsvm"
    _write_libsvm(str(data))
    out = tmp_path / "final.model"
    faults.inject("torn_write", 200, path_sub="final.model")
    from xgboost_tpu.cli import main
    assert main([f"data={data}", "task=train", "num_round=3", "silent=2",
                 "objective=binary:logistic", "max_bin=16",
                 f"model_out={out}"]) == 0
    with pytest.raises(ModelIntegrityError):
        xgb.Booster(model_file=str(out))


# ----------------------------------------------- checkpoint-ring fallback
def _write_libsvm(path, n=300, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] > 0.5).astype(int)
    with open(path, "w") as fh:
        for i in range(n):
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(f))
            fh.write(f"{y[i]} {feats}\n")


def _model_state(path):
    return xgb.Booster(model_file=str(path)).gbtree.get_state()


def test_load_checkpoint_falls_back_past_truncated_newest(model, tmp_path):
    """Satellite regression: a truncated newest ckpt-* member no longer
    aborts the gang restart — the older replica loads, the bad file is
    quarantined."""
    from xgboost_tpu.cli import _load_checkpoint, _save_checkpoint
    bst_a, X, _ = model
    bst_b, _ = _train(seed=7, rounds=6, max_depth=2)
    ck = str(tmp_path / "ck")
    _save_checkpoint(ck, bst_a, 3)
    _save_checkpoint(ck, bst_b, 4)
    newest = os.path.join(ck, "ckpt-000004.model")
    raw = open(newest, "rb").read()
    open(newest, "wb").write(raw[:len(raw) // 2])  # truncate mid-file

    rm = reliability_metrics()
    fb0, q0 = rm.ring_fallbacks.value, rm.quarantines.value
    fresh = xgb.Booster()
    got, version = _load_checkpoint(ck, fresh, {})
    assert version == 3  # fell back to the older ring member
    ref = bst_a.predict(xgb.DMatrix(X[:10]))
    assert np.array_equal(got.predict(xgb.DMatrix(X[:10])), ref)
    assert os.path.exists(newest + ".corrupt")
    assert not os.path.exists(newest)
    assert rm.ring_fallbacks.value - fb0 == 1
    assert rm.quarantines.value - q0 == 1
    # a later _save_checkpoint of version 4 replaces the slot cleanly
    _save_checkpoint(ck, bst_b, 4)
    got2, v2 = _load_checkpoint(ck, xgb.Booster(), {})
    assert v2 == 4


def test_all_ring_members_corrupt_starts_fresh(tmp_path):
    from xgboost_tpu.cli import _load_checkpoint
    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / "ckpt-000001.model").write_bytes(b"garbage")
    (ck / "ckpt-000002.model").write_bytes(b"PK\x03\x04 torn")
    bst, version = _load_checkpoint(str(ck), xgb.Booster(), {})
    assert version == 0
    assert sorted(f for f in os.listdir(ck) if f.endswith(".corrupt")) == [
        "ckpt-000001.model.corrupt", "ckpt-000002.model.corrupt"]


def test_transient_read_error_does_not_quarantine(model, tmp_path):
    """A transient OSError (EIO/EMFILE blip) on a ring member falls
    back WITHOUT quarantining the possibly-good file — the next
    restart can retry it."""
    from xgboost_tpu.cli import _load_checkpoint, _save_checkpoint
    bst_a, X, _ = model
    bst_b, _ = _train(seed=8, rounds=6, max_depth=2)
    ck = str(tmp_path / "ck")
    _save_checkpoint(ck, bst_a, 3)
    _save_checkpoint(ck, bst_b, 4)
    newest = os.path.join(ck, "ckpt-000004.model")
    # simulate a transient I/O failure: open() raises OSError (ENOENT
    # via a dangling symlink — chmod tricks don't work under root)
    os.rename(newest, newest + ".real")
    os.symlink(newest + ".gone", newest)
    try:
        got, version = _load_checkpoint(ck, xgb.Booster(), {})
        assert version == 3  # older member served this restart
        assert os.path.lexists(newest)  # NOT renamed to .corrupt
        assert not os.path.exists(newest + ".corrupt")
    finally:
        os.remove(newest)
        os.rename(newest + ".real", newest)
    # blip cleared: the next restart loads the newest member normally
    got2, v2 = _load_checkpoint(ck, xgb.Booster(), {})
    assert v2 == 4


def test_drain_grace_expiry_is_bounded_with_wedged_request(model, tmp_path):
    """A wedged device call must not defeat the drain grace: drain()
    returns once the grace expires even though the request never
    finishes (its daemon handler thread is reaped at process exit)."""
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    srv = run_server(p, port=0, min_bucket=8, max_bucket=32,
                     max_wait_ms=1, poll_sec=0, warmup=False,
                     quiet=True, block=False)
    base = f"http://127.0.0.1:{srv.port}"
    body = b"0.1,0.2,0.3,0.4,0.5"
    gate, entered = threading.Event(), threading.Event()
    real_fn = srv.batcher.predict_fn

    def wedged(Xq, **kw):
        entered.set()
        gate.wait(30.0)  # "wedged" until the test cleans up
        return real_fn(Xq, **kw)

    srv.batcher.predict_fn = wedged
    t = threading.Thread(target=lambda: urllib.request.urlopen(
        urllib.request.Request(base + "/predict", data=body,
                               method="POST")), daemon=True)
    t.start()
    assert entered.wait(5.0)
    # release the wedge shortly AFTER the grace expires, so close()'s
    # bounded worker join doesn't stretch the test
    threading.Timer(0.6, gate.set).start()
    t0 = time.perf_counter()
    dur = srv.drain(grace=0.2)
    assert srv.state == "stopped"
    assert time.perf_counter() - t0 < 10.0  # bounded, not wedged-forever
    assert dur >= 0.2
    gate.set()


def test_total_ring_failure_restores_booster_config(tmp_path):
    """A checkpoint whose HEADER parses but whose state arrays are
    corrupt must not leak its param/objective into the fresh-start
    booster when every ring member fails."""
    import io
    from xgboost_tpu.cli import _load_checkpoint
    ck = tmp_path / "ck"
    ck.mkdir()
    # valid footer + valid npz + valid header, corrupt state: load gets
    # past the param/objective assignment, then raises in from_state
    header = {"magic": "xgbtpu001",
              "param": {"objective": "multi:softmax", "num_class": 7},
              "num_feature": 99, "attributes": {"evil": "1"},
              "best_iteration": 5}
    buf = io.BytesIO()
    np.savez(buf, header=np.frombuffer(json.dumps(header).encode(),
                                       np.uint8),
             bogus=np.zeros(3, np.float32))
    (ck / "ckpt-000002.model").write_bytes(add_footer(buf.getvalue()))

    bst = xgb.Booster({"objective": "binary:logistic"})
    got, version = _load_checkpoint(str(ck), bst,
                                    {"objective": "binary:logistic"})
    assert version == 0
    assert os.path.exists(ck / "ckpt-000002.model.corrupt")
    # the caller's config survived; nothing from the corrupt header did
    assert got.param.objective == "binary:logistic"
    assert got.num_feature == 0 and got.attributes == {}
    assert got.best_iteration == -1 and got.gbtree is None


def test_kill_plus_torn_checkpoint_recovers_bit_identical(tmp_path, capfd):
    """Acceptance (a), the full gauntlet: the newest ring member is TORN
    by an injected fault, the worker then dies at an injected collective
    coordinate, the keepalive restart quarantines the torn member, falls
    back to the older replica, and the finished model is bit-identical
    to an uninterrupted run."""
    from xgboost_tpu.cli import main
    data = tmp_path / "train.libsvm"
    _write_libsvm(str(data))
    common = [f"data={data}", "task=train", "num_round=5", "silent=2",
              "objective=binary:logistic", "max_depth=3", "eta=0.5",
              "max_bin=16",
              # per-round segments: mock replay no longer blocks fusion,
              # and this test's torn-member/fallback choreography needs
              # the ring written at every round boundary
              "rounds_per_dispatch=1"]
    m_ref = tmp_path / "ref.model"
    assert main(common + [f"model_out={m_ref}",
                          f"checkpoint_dir={tmp_path / 'ck_ref'}"]) == 0

    # round 2's checkpoint (version 3) is torn at byte 100; the worker
    # dies entering round 3 — restart must fall back to version 2
    faults.inject("torn_write", 100, path_sub="ckpt-000003")
    capfd.readouterr()
    m_got = tmp_path / "got.model"
    rm = reliability_metrics()
    fb0 = rm.ring_fallbacks.value
    assert main(common + [f"model_out={m_got}",
                          f"checkpoint_dir={tmp_path / 'ck_got'}",
                          "mock=3,0,0", "keepalive=1"]) == 0
    err = capfd.readouterr().err
    assert err.count("[mock]") == 1, err           # the death fired
    assert "falling back" in err                   # the fallback fired
    assert "resume at round 2" in err              # older replica used
    assert rm.ring_fallbacks.value - fb0 == 1

    ref, got = _model_state(m_ref), _model_state(m_got)
    assert set(ref) == set(got)
    for k in ref:
        np.testing.assert_array_equal(ref[k], got[k], err_msg=k)


def test_cli_faults_param_installs_spec(tmp_path):
    """The faults= CLI parameter (env-free injection for subprocess
    drivers) reaches the write seam."""
    from xgboost_tpu.cli import main
    data = tmp_path / "train.libsvm"
    _write_libsvm(str(data))
    out = tmp_path / "m.model"
    assert main([f"data={data}", "task=train", "num_round=2", "silent=2",
                 "objective=binary:logistic", "max_bin=16",
                 f"model_out={out}", "faults=bit_flip=50@m.model"]) == 0
    with pytest.raises(ModelIntegrityError):
        xgb.Booster(model_file=str(out))


# --------------------------------------------------- registry poisoning
def test_registry_rejects_corrupt_file_before_engine_build(model, tmp_path):
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    bad = bytearray(open(p, "rb").read())
    bad[120] ^= 1
    open(p, "wb").write(bytes(bad))
    with pytest.raises(ModelIntegrityError):
        ModelRegistry(p, warmup=False, poll_sec=0,
                      min_bucket=8, max_bucket=32)


def test_registry_poisons_corrupt_overwrite(model, tmp_path):
    """Satellite: a persistently corrupt model file is built exactly
    once, then hashed-and-rejected (no re-warm) until the file changes
    again."""
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    good = open(p, "rb").read()
    reg = ModelRegistry(p, warmup=False, poll_sec=0,
                        min_bucket=8, max_bucket=32)
    ref = bst.predict(xgb.DMatrix(X[:8]))
    assert np.array_equal(reg.predict(X[:8]), ref)

    bad = bytearray(good)
    bad[99] ^= 0x20
    open(p, "wb").write(bytes(bad))
    a0 = reg.build_attempts
    p0 = reliability_metrics().poisoned_reloads.value
    assert reg.check_reload() is False
    assert reg.build_attempts - a0 == 1
    assert reg.poisoned and reg.reload_failures == 1
    assert "CRC32" in reg.last_reload_error
    for _ in range(5):  # the 1 s poll, compressed: NO rebuild, NO rewarm
        assert reg.check_reload() is False
    assert reg.build_attempts - a0 == 1
    assert reliability_metrics().poisoned_reloads.value > p0
    # old model still serving
    assert np.array_equal(reg.predict(X[:8]), ref)

    # rewriting the SAME bad bytes (new mtime) is still rejected by hash
    time.sleep(0.01)
    open(p, "wb").write(bytes(bad))
    assert reg.check_reload() is False
    assert reg.build_attempts - a0 == 1

    # a genuinely new good model clears the poisoning
    bst_b, _ = _train(seed=5, rounds=6, max_depth=2)
    bst_b.save_model(p)
    assert reg.check_reload() is True
    assert reg.version == 2 and not reg.poisoned
    assert reg.last_reload_error is None
    assert np.array_equal(reg.predict(X[:8]),
                          bst_b.predict(xgb.DMatrix(X[:8])))


def test_registry_injected_reload_failure_poisons(model, tmp_path):
    """The reload seam (faults.check('reload')) poisons like organic
    corruption: one build attempt, retried only on the next change."""
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    reg = ModelRegistry(p, warmup=False, poll_sec=0,
                        min_bucket=8, max_bucket=32)
    bst_b, _ = _train(seed=3, rounds=5, max_depth=2)
    faults.inject("reload", path_sub="m.model")
    bst_b.save_model(p)
    a0 = reg.build_attempts
    assert reg.check_reload() is False
    assert reg.poisoned and "InjectedFault" in reg.last_reload_error
    assert reg.check_reload() is False
    assert reg.build_attempts - a0 == 1
    # fault disarmed after firing once; the NEXT file change reloads
    bst_b.save_model(p)       # same content... poisoned hash matches
    assert reg.check_reload() is False
    bst_c, _ = _train(seed=4, rounds=5, max_depth=2)
    bst_c.save_model(p)
    assert reg.check_reload() is True
    assert reg.version == 2


def test_rollback_to_live_content_clears_degraded(model, tmp_path):
    """Operator remediation by ROLLING BACK the file: restoring the
    live model's exact bytes clears the poisoned/degraded state (the
    on-disk file is no longer known-bad) without a reload."""
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    good = open(p, "rb").read()
    reg = ModelRegistry(p, warmup=False, poll_sec=0,
                        min_bucket=8, max_bucket=32)
    bad = bytearray(good)
    bad[111] ^= 1
    open(p, "wb").write(bytes(bad))
    assert reg.check_reload() is False
    assert reg.poisoned
    open(p, "wb").write(good)              # roll the push back
    assert reg.check_reload() is False     # same content as live: no-op
    assert not reg.poisoned and reg.last_reload_error is None
    assert reg.version == 1


def test_forced_reload_sees_through_preserved_stat(model, tmp_path):
    """A rewrite that preserves mtime AND size (rsync -a / cp -p of a
    same-sized file) is invisible to the stat-compare poll by design;
    check_reload(force=True) must re-read and detect it anyway."""
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    st = os.stat(p)
    reg = ModelRegistry(p, warmup=False, poll_sec=0,
                        min_bucket=8, max_bucket=32)
    bad = bytearray(open(p, "rb").read())
    bad[123] ^= 1                                  # same size
    open(p, "wb").write(bytes(bad))
    os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns))  # same mtime
    assert reg.check_reload() is False             # poll: blind, by design
    assert not reg.poisoned                        # ...and unaware
    assert reg.check_reload(force=True) is False   # forced: READ the file
    assert reg.poisoned and "CRC32" in reg.last_reload_error
    ref = bst.predict(xgb.DMatrix(X[:5]))
    assert np.array_equal(reg.predict(X[:5]), ref)  # old model serving


def test_forced_reload_retries_after_transient_failure(model, tmp_path):
    """check_reload(force=True) — the /-/reload endpoint — bypasses the
    poisoned skip: a GOOD file whose first build failed transiently
    (device hiccup, injected fault) is retried on demand instead of
    being pinned out until its bytes change."""
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    reg = ModelRegistry(p, warmup=False, poll_sec=0,
                        min_bucket=8, max_bucket=32)
    bst_b, _ = _train(seed=6, rounds=5, max_depth=2)
    faults.inject("reload", path_sub="m.model")  # fires ONCE (transient)
    bst_b.save_model(p)
    assert reg.check_reload() is False            # transient failure
    assert reg.poisoned
    assert reg.check_reload() is False            # unforced: skipped
    assert reg.check_reload(force=True) is True   # forced: retried, lives
    assert reg.version == 2 and not reg.poisoned
    assert np.array_equal(reg.predict(X[:6]),
                          bst_b.predict(xgb.DMatrix(X[:6])))


# ------------------------------------------------ serving under traffic
def test_corrupt_overwrite_under_traffic_zero_failures(model, tmp_path):
    """Acceptance (b): corrupt bytes hit the watched file mid-traffic —
    zero failed predictions, one build attempt, /healthz degraded with
    the error, then recovery on the next good write."""
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    good = open(p, "rb").read()
    srv = run_server(p, port=0, min_bucket=8, max_bucket=32,
                     max_wait_ms=1, poll_sec=0, warmup=False,
                     quiet=True, block=False)
    base = f"http://127.0.0.1:{srv.port}"
    body = "\n".join(",".join(f"{v:.6f}" for v in r)
                     for r in X[:5]).encode()

    def post(route, data=b""):
        return json.load(urllib.request.urlopen(urllib.request.Request(
            base + route, data=data, method="POST")))

    stop = threading.Event()
    errors, n_ok = [], [0]

    def hammer():
        while not stop.is_set():
            try:
                post("/predict", body)
                n_ok[0] += 1
            except BaseException as e:  # noqa: BLE001 — recorded, asserted
                errors.append(e)

    try:
        ts = [threading.Thread(target=hammer) for _ in range(3)]
        for t in ts:
            t.start()
        time.sleep(0.15)
        bad = bytearray(good)
        bad[140] ^= 1
        open(p, "wb").write(bytes(bad))
        a0 = srv.registry.build_attempts
        assert post("/-/reload")["reloaded"] is False  # one build, fails
        for _ in range(3):  # the poll loop: hash-rejected, no rebuild
            assert srv.registry.check_reload() is False
        assert srv.registry.build_attempts - a0 == 1  # exactly one build
        h = json.load(urllib.request.urlopen(base + "/healthz"))
        assert h["status"] == "degraded"
        assert h["reload_failures"] == 1
        assert "CRC32" in h["last_reload_error"]
        time.sleep(0.1)
        stop.set()
        for t in ts:
            t.join(10.0)
        assert not errors, f"requests failed during corruption: {errors[:3]}"
        assert n_ok[0] > 0
        # recovery: good model B goes live, healthz back to ok
        bst_b, _ = _train(seed=21, rounds=6, max_depth=2)
        bst_b.save_model(p)
        assert post("/-/reload")["reloaded"] is True
        h = json.load(urllib.request.urlopen(base + "/healthz"))
        assert h["status"] == "ok" and h["model_version"] == 2
        assert h["last_reload_error"] is None
    finally:
        stop.set()
        srv.shutdown()


def test_drain_state_machine(model, tmp_path):
    """Acceptance (c): drain stops admitting /predict (503), finishes
    the in-flight request, reports state via /healthz, records the
    drain duration, then stops."""
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    srv = run_server(p, port=0, min_bucket=8, max_bucket=32,
                     max_wait_ms=1, poll_sec=0, warmup=False,
                     quiet=True, block=False)
    base = f"http://127.0.0.1:{srv.port}"
    body = "\n".join(",".join(f"{v:.6f}" for v in r)
                     for r in X[:3]).encode()
    # make the in-flight request controllable: gate the batcher's
    # predict_fn (the REAL engine runs once the gate opens)
    gate, entered = threading.Event(), threading.Event()
    real_fn = srv.batcher.predict_fn

    def gated(Xq, **kw):
        entered.set()
        gate.wait(10.0)
        return real_fn(Xq, **kw)

    srv.batcher.predict_fn = gated
    result = [None]

    def inflight():
        result[0] = json.load(urllib.request.urlopen(
            urllib.request.Request(base + "/predict", data=body,
                                   method="POST")))

    t = threading.Thread(target=inflight)
    t.start()
    assert entered.wait(5.0)
    assert srv.state == "serving" and srv.inflight == 1
    drain_dur = [None]
    dt = threading.Thread(target=lambda: drain_dur.__setitem__(
        0, srv.drain(grace=10.0)))
    dt.start()
    for _ in range(200):
        if srv.state == "draining":
            break
        time.sleep(0.01)
    assert srv.state == "draining"
    # healthz still answers and reports the state
    h = json.load(urllib.request.urlopen(base + "/healthz"))
    assert h["state"] == "draining"
    # new predictions are refused with 503
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(urllib.request.Request(
            base + "/predict", data=body, method="POST"))
    assert ei.value.code == 503
    # the in-flight request finishes successfully
    gate.set()
    t.join(10.0)
    dt.join(15.0)
    assert result[0] is not None and result[0]["rows"] == 3
    assert srv.state == "stopped"
    assert drain_dur[0] is not None and drain_dur[0] > 0
    assert reliability_metrics().drain_seconds.value > 0


def test_sigterm_handler_triggers_drain(model, tmp_path):
    """The SIGTERM path: the handler spawns the drain (it cannot run on
    the serving thread), ending at state=stopped."""
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    srv = run_server(p, port=0, min_bucket=8, max_bucket=32,
                     max_wait_ms=1, poll_sec=0, warmup=False,
                     quiet=True, block=False)
    try:
        import signal as _signal
        srv._handle_sigterm(_signal.SIGTERM, None)
        for _ in range(500):
            if srv.state == "stopped":
                break
            time.sleep(0.01)
        assert srv.state == "stopped"
    finally:
        srv.shutdown()


def test_oversized_body_rejected_without_buffering(model, tmp_path):
    """A Content-Length beyond serve_max_body_mb is refused with 413
    BEFORE any body bytes are read (reject-don't-buffer at the HTTP
    layer too)."""
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    srv = run_server(p, port=0, min_bucket=8, max_bucket=32,
                     max_wait_ms=1, poll_sec=0, warmup=False,
                     max_body_mb=0.001, quiet=True, block=False)  # 1 KiB
    try:
        base = f"http://127.0.0.1:{srv.port}"
        small = b"0.1,0.2,0.3,0.4,0.5"
        r = json.load(urllib.request.urlopen(urllib.request.Request(
            base + "/predict", data=small, method="POST")))
        assert r["rows"] == 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/predict", data=b"x" * 4096, method="POST"))
        assert ei.value.code == 413
    finally:
        srv.shutdown()


def test_libsvm_out_of_range_index_is_client_error(model, tmp_path):
    """A libsvm row addressing a feature beyond the model's width is a
    400 (like the CSV too-many-columns path), not silently-dropped
    features and a confident wrong answer."""
    from xgboost_tpu.serving.http import parse_libsvm_rows
    with pytest.raises(ValueError, match="out of range"):
        parse_libsvm_rows("1 2:0.5 40:0.25", num_feature=5)
    # in range still parses (label column tolerated)
    out = parse_libsvm_rows("1 2:0.5 4:0.25", num_feature=5)
    assert out.shape == (1, 5) and out[0, 2] == np.float32(0.5)
    bst, X, _ = model
    p = str(tmp_path / "m.model")
    bst.save_model(p)
    srv = run_server(p, port=0, min_bucket=8, max_bucket=32,
                     max_wait_ms=1, poll_sec=0, warmup=False,
                     quiet=True, block=False)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/predict?format=libsvm", data=b"1 0:0.1 99:0.5",
                method="POST"))
        assert ei.value.code == 400
    finally:
        srv.shutdown()


# --------------------------------------------------- batcher shedding
def test_batcher_sheds_abandoned_requests():
    """Satellite (d): a request whose caller timed out is skipped by the
    worker — no device dispatch for a result nobody reads."""
    release = threading.Event()
    calls = []

    def predict_fn(X, output_margin=False):
        calls.append(X.shape[0])
        release.wait(5.0)
        return np.zeros(X.shape[0], np.float32)

    b = MicroBatcher(predict_fn, max_batch_rows=4, max_wait_ms=1,
                     max_queue_rows=100)
    shed0 = reliability_metrics().shed_requests.value
    try:
        t = threading.Thread(target=lambda: b.submit(np.zeros((4, 2))))
        t.start()
        time.sleep(0.05)  # worker picked up the first batch and blocked
        with pytest.raises(TimeoutError):
            b.submit(np.zeros((2, 2)), timeout=0.05)  # queued + abandoned
        release.set()
        t.join(5.0)
    finally:
        release.set()
        b.close()
    # the abandoned request was never dispatched: only the first batch
    # reached predict_fn
    assert calls == [4]
    assert reliability_metrics().shed_requests.value - shed0 == 1


def test_batcher_sheds_only_abandoned_in_mixed_batch():
    """Abandoned and live requests coalesced into the same batch: the
    live one gets its rows, the abandoned one is dropped."""
    release = threading.Event()
    calls = []

    def predict_fn(X, output_margin=False):
        calls.append(X.shape[0])
        if len(calls) == 1:
            release.wait(5.0)
        return X[:, 0].copy()

    b = MicroBatcher(predict_fn, max_batch_rows=100, max_wait_ms=30,
                     max_queue_rows=1000)
    try:
        t1 = threading.Thread(target=lambda: b.submit(
            np.zeros((2, 2), np.float32)))
        t1.start()
        time.sleep(0.05)  # worker blocked inside batch 1
        with pytest.raises(TimeoutError):
            b.submit(np.full((3, 2), 7.0, np.float32), timeout=0.05)
        res = [None]
        t2 = threading.Thread(target=lambda: res.__setitem__(
            0, b.submit(np.full((2, 2), 9.0, np.float32), timeout=5.0)))
        t2.start()
        time.sleep(0.05)
        release.set()
        t1.join(5.0)
        t2.join(5.0)
        assert np.array_equal(res[0], np.full(2, 9.0, np.float32))
        # the abandoned 3-row request never contributed to a dispatch
        assert 3 not in calls and 5 not in calls
    finally:
        release.set()
        b.close()


# ------------------------------------------- publish-vs-poll concurrency
def test_concurrent_publish_vs_poll_never_torn_never_double(model,
                                                            tmp_path):
    """ModelRegistry.check_reload racing in-flight atomic publishes
    (the continuous-training pipeline's steady state, PIPELINE.md):
    the poller must never build an engine from torn bytes — every
    publish is atomic_write, so every read observes a complete file —
    and must never build the same content hash twice in a row (the
    live-hash short-circuit)."""
    bst, X, path = model
    p = str(tmp_path / "race.model")
    bst.save_model(p)
    with open(p, "rb") as f:
        raw_a = f.read()
    bst_b, _ = _train(seed=7, rounds=3)
    pb = str(tmp_path / "b.model")
    bst_b.save_model(pb)
    with open(pb, "rb") as f:
        raw_b = f.read()
    assert raw_a != raw_b

    reg = ModelRegistry(p, poll_sec=0, warmup=False,
                        min_bucket=8, max_bucket=16)
    built = []
    orig_build = reg._build_engine

    def recording_build(raw):
        import hashlib
        built.append(hashlib.sha256(raw).hexdigest())
        return orig_build(raw)

    reg._build_engine = recording_build
    base_failures = reg.reload_failures
    stop = threading.Event()

    def publisher():
        flip = False
        while not stop.is_set():
            atomic_write(p, raw_b if flip else raw_a)
            flip = not flip
            time.sleep(0.002)

    def poller():
        while not stop.is_set():
            reg.check_reload()

    threads = [threading.Thread(target=publisher)] + [
        threading.Thread(target=poller) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(1.2)
    stop.set()
    for t in threads:
        t.join(30.0)

    # no torn bytes were ever seen: every build verified + loaded
    assert reg.reload_failures == base_failures
    assert len(built) >= 2  # the race actually exercised reloads
    # never the same content twice in a row (each build was a change)
    assert all(h1 != h2 for h1, h2 in zip(built, built[1:]))

    # and a same-bytes rewrite (mtime changes, content does not) never
    # rebuilds: the short-circuit compares against the LIVE engine hash
    reg.check_reload()
    builds_before = len(built)
    with open(p, "rb") as f:
        current = f.read()
    for _ in range(5):
        atomic_write(p, current)
        reg.check_reload()
    assert len(built) == builds_before
    reg.stop()


# ------------------------------------------------------------- metrics
def test_metrics_page_includes_reliability_counters():
    m = ServingMetrics()
    text = m.render()
    for name in ("xgbtpu_reliability_integrity_failures_total",
                 "xgbtpu_reliability_ckpt_ring_fallbacks_total",
                 "xgbtpu_reliability_quarantined_files_total",
                 "xgbtpu_reliability_poisoned_reload_skips_total",
                 "xgbtpu_reliability_shed_requests_total",
                 "xgbtpu_reliability_drain_seconds"):
        assert name in text, f"{name} missing from /metrics"


# ------------------------------------------------------- chaos driver
@pytest.mark.slow
def test_chaos_loop_driver(tmp_path):
    """The tools/chaos_loop.py driver: every randomized kill/corruption
    run recovers bit-identical and CHAOS.json records it."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import chaos_loop
    finally:
        sys.path.pop(0)
    out = tmp_path / "CHAOS.json"
    rc = chaos_loop.main(["--runs", "3", "--rounds", "5", "--seed", "1",
                          "--out", str(out), "--workdir", str(tmp_path)])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["runs"] == 3
    assert report["bit_identical"] == 3
    assert report["mismatches"] == 0

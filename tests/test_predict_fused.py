"""Round-7 transfer-wall stack: fused quantize+traverse, direct-buffer
uploads, the double-buffered upload seam, and the device-resident
feature store (serving/featurestore.py).

Parity suite: the fused program must be BIT-identical to the two-step
quantize-then-traverse path across block boundaries, N not a block
multiple, multiclass, and ntree_limit windows; the feature store must
gather exactly the rows that were put, evict in LRU order under its
byte budget, and survive a registry hot-reload by rebinning resident
raw rows against the new model's cuts.  A ``recompile_guard`` budget
pins the fused program ladder.  (No mesh usage — no AxisType gate.)
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest


def _train(params=None, n=400, f=8, rounds=7, seed=0, num_class=0):
    import xgboost_tpu as xgb

    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    X[::11, f // 2] = np.nan                      # missing -> bin 0
    if num_class:
        y = (np.nan_to_num(X[:, 0]) * num_class).astype(np.int64) \
            % num_class
        y = y.astype(np.float32)
        p = {"objective": "multi:softmax", "num_class": num_class}
    else:
        y = (np.nan_to_num(X[:, 0])
             + 0.3 * np.nan_to_num(X[:, 1]) > 0.6).astype(np.float32)
        p = {"objective": "binary:logistic"}
    p.update({"max_depth": 4, "eta": 0.3, "silent": 1})
    p.update(params or {})
    d = xgb.DMatrix(X, label=y)
    return xgb.train(p, d, rounds), X, d


def _two_step(bst, X):
    """Reference margins via the explicit two-step path."""
    import jax.numpy as jnp
    from xgboost_tpu.binning import bin_dense_device
    binned = bin_dense_device(X, bst.gbtree.cuts.cut_values)
    return np.asarray(bst.gbtree.predict_margin(
        binned, jnp.zeros((), jnp.float32)))


# ------------------------------------------------------------- fused core
def test_fused_margin_bit_parity():
    """predict_margin_fused == quantize-then-predict_margin, for the
    scan baseline AND a chunked layout, including NaN rows."""
    import jax.numpy as jnp
    bst, X, _ = _train(rounds=7)
    gbt = bst.gbtree
    ref = _two_step(bst, X)
    Xd = jnp.asarray(X)
    for chunk in (0, 4):
        saved = gbt.pred_chunk
        gbt.pred_chunk = chunk
        try:
            fused = np.asarray(gbt.predict_margin_fused(
                Xd, jnp.zeros((), jnp.float32)))
        finally:
            gbt.pred_chunk = saved
        assert np.array_equal(ref, fused), chunk


def test_fused_ntree_limit_windows():
    import jax.numpy as jnp
    bst, X, _ = _train(rounds=9)
    gbt = bst.gbtree
    Xd = jnp.asarray(X)
    from xgboost_tpu.binning import bin_dense_device
    binned = bin_dense_device(Xd, gbt.cuts.cut_values)
    for lim in (1, 3, 5, 9):
        ref = np.asarray(gbt.predict_margin(
            binned, jnp.zeros((), jnp.float32), lim))
        fused = np.asarray(gbt.predict_margin_fused(
            Xd, jnp.zeros((), jnp.float32), lim))
        assert np.array_equal(ref, fused), lim


def test_learner_fused_vs_two_step_end_to_end(monkeypatch):
    """Booster.predict with the fused default equals the
    XGBTPU_PREDICT_FUSED=0 two-step baseline — including a blocked run
    where N is NOT a block multiple (block boundaries are invisible)."""
    import xgboost_tpu as xgb
    bst, X, _ = _train(rounds=5, n=501, f=6)     # 501: not a multiple
    ref = bst.predict(xgb.DMatrix(X))            # fused default
    monkeypatch.setenv("XGBTPU_PREDICT_FUSED", "0")
    two = bst.predict(xgb.DMatrix(X))
    monkeypatch.delenv("XGBTPU_PREDICT_FUSED")
    assert np.array_equal(ref, two)
    # ~4 blocks, last one ragged
    monkeypatch.setenv("XGBTPU_BIN_BLOCK_BYTES", str(501 * 6 * 4 // 4))
    assert np.array_equal(ref, bst.predict(xgb.DMatrix(X)))
    # the direct-buffer satellite: a C-contiguous f32 ndarray skips the
    # CSR round-trip and uploads the caller's own blocks
    assert np.array_equal(ref, bst.predict(X))
    assert np.array_equal(ref, bst.predict(np.asfortranarray(X)))


def test_learner_fused_multiclass_and_base_margin(monkeypatch):
    import xgboost_tpu as xgb
    bst, X, _ = _train(rounds=4, num_class=3)
    ref = bst.predict(xgb.DMatrix(X))
    monkeypatch.setenv("XGBTPU_PREDICT_FUSED", "0")
    assert np.array_equal(ref, bst.predict(xgb.DMatrix(X)))
    monkeypatch.delenv("XGBTPU_PREDICT_FUSED")
    # a user base_margin rides the per-block slices bit-identically
    bm = np.linspace(-1, 1, X.shape[0] * 3).astype(np.float32)
    d1 = xgb.DMatrix(X)
    d1.set_base_margin(bm)
    ref_bm = bst.predict(d1, output_margin=True)
    monkeypatch.setenv("XGBTPU_PREDICT_FUSED", "0")
    d2 = xgb.DMatrix(X)
    d2.set_base_margin(bm)
    assert np.array_equal(ref_bm, bst.predict(d2, output_margin=True))


def test_upload_depth_seam(monkeypatch):
    """XGBTPU_PREDICT_UPLOAD_DEPTH in {0 sync, 1, 2} is value-invisible
    on a blocked fused prediction."""
    import xgboost_tpu as xgb
    bst, X, _ = _train(rounds=3, n=300, f=6)
    monkeypatch.setenv("XGBTPU_BIN_BLOCK_BYTES", str(300 * 6 * 4 // 3))
    ref = bst.predict(xgb.DMatrix(X))
    for depth in ("0", "1", "2"):
        monkeypatch.setenv("XGBTPU_PREDICT_UPLOAD_DEPTH", depth)
        assert np.array_equal(ref, bst.predict(xgb.DMatrix(X))), depth


def test_transfer_counters_account_uploads():
    """Every one-off predict upload lands on
    xgbtpu_predict_transfer_{bytes_total,seconds}."""
    import xgboost_tpu as xgb
    from xgboost_tpu.obs.metrics import predict_metrics
    bst, X, _ = _train(rounds=2, n=200, f=6)
    pm = predict_metrics()
    b0, n0 = pm.transfer_bytes.value, pm.transfer_seconds.count
    bst.predict(xgb.DMatrix(X))
    assert pm.transfer_bytes.value - b0 == X.nbytes
    assert pm.transfer_seconds.count > n0


def test_fused_compile_budget(recompile_guard):
    """Growing T = 1..3*chunk through the FUSED program compiles once
    per ladder rung (same budget as the two-step traversal), and a
    second pass compiles nothing."""
    import jax
    import jax.numpy as jnp
    from xgboost_tpu.models.tree import (pad_predict_stack,
                                         padded_tree_count,
                                         predict_margin_fused)
    bst, X, _ = _train(rounds=12)
    chunk = 4
    stack, group = bst.gbtree._stack(0)
    cuts = bst.gbtree.cut_values_dev
    Xd = jnp.asarray(X)
    base = jnp.zeros((), jnp.float32)
    windows = []
    for T in range(1, 13):
        win = (jax.tree.map(lambda x: x[:T], stack), group[:T])
        windows.append(win)
        jax.block_until_ready(pad_predict_stack(win[0], win[1],
                                                chunk)[:2])
    expected = len({padded_tree_count(T, chunk) for T in range(1, 13)})
    assert expected == 5  # {1, 2, 4, 8, 12}
    with recompile_guard.expect(expected):
        for st, gr in windows:
            jax.block_until_ready(predict_margin_fused(
                st, gr, Xd, cuts, base, 4, 1, tree_chunk=chunk))
    with recompile_guard.expect(0):
        for st, gr in windows:
            jax.block_until_ready(predict_margin_fused(
                st, gr, Xd, cuts, base, 4, 1, tree_chunk=chunk))


# ---------------------------------------------------------- fused serving
def test_engine_fused_parity_and_zero_compiles(recompile_guard):
    """The fused AOT bucket executables serve bit-identically to
    Learner.predict and keep the zero-steady-state-compile invariant."""
    import xgboost_tpu as xgb
    from xgboost_tpu.serving import PredictEngine
    bst, X, _ = _train(rounds=5)
    eng = PredictEngine(bst, min_bucket=8, max_bucket=64, warmup=True)
    assert eng.describe()["fused"] is True
    sizes = (3, 8, 17, 64, 100)                  # incl. chunk-through-top
    # learner references OUTSIDE the guard (Learner.predict compiles its
    # own per-N programs; the invariant under test is the ENGINE's)
    refs = {n: bst.predict(xgb.DMatrix(X[:n])) for n in sizes}
    with recompile_guard.expect(0):
        for n in sizes:
            assert np.array_equal(eng.predict(X[:n]), refs[n]), n


def test_engine_two_step_env_fallback(monkeypatch):
    """XGBTPU_SERVE_FUSED=0 restores the host-quantize baseline with
    identical outputs."""
    from xgboost_tpu.serving import PredictEngine
    bst, X, _ = _train(rounds=4)
    fused = PredictEngine(bst, min_bucket=8, max_bucket=32)
    monkeypatch.setenv("XGBTPU_SERVE_FUSED", "0")
    two = PredictEngine(bst, min_bucket=8, max_bucket=32)
    assert two.describe()["fused"] is False
    for n in (5, 20):
        assert np.array_equal(fused.predict(X[:n]), two.predict(X[:n]))


def test_engine_counts_transfer_bytes():
    from xgboost_tpu.obs.metrics import predict_metrics
    from xgboost_tpu.serving import PredictEngine
    bst, X, _ = _train(rounds=3, f=6)
    eng = PredictEngine(bst, min_bucket=8, max_bucket=32, warmup=True)
    pm = predict_metrics()
    b0 = pm.transfer_bytes.value
    eng.predict(X[:5])
    # 5 rows pad to the 8-bucket: the uploaded f32 buffer is 8 x F
    assert pm.transfer_bytes.value - b0 == 8 * 6 * 4


# ------------------------------------------------------------ featurestore
def test_featurestore_put_gather_parity_and_lru():
    from xgboost_tpu.serving.featurestore import FeatureStore
    F = 4
    # budget for exactly 3 rows
    store = FeatureStore(F, budget_mb=3 * F * 4 / (1 << 20))
    assert store.capacity == 3
    rng = np.random.RandomState(0)
    X = rng.rand(5, F).astype(np.float32)
    store.put(["a", "b", "c"], X[:3])
    got, missing = store.gather(["b", "a"])
    assert missing == []
    assert np.array_equal(np.asarray(got), X[[1, 0]])
    # gather refreshed b,a — "c" is now LRU and evicts first
    store.put(["d"], X[3:4])
    _, missing = store.gather(["c"])
    assert missing == ["c"]
    got, missing = store.gather(["a", "b", "d"])
    assert missing == []
    assert np.array_equal(np.asarray(got), X[[0, 1, 3]])
    # updating a resident id keeps its slot and rewrites the row
    store.put(["a"], X[4:5])
    got, _ = store.gather(["a"])
    assert np.array_equal(np.asarray(got), X[4:5])
    assert store.describe()["resident_bytes"] == 3 * F * 4


def test_featurestore_gather_pads_with_nan_rows():
    from xgboost_tpu.serving.featurestore import FeatureStore
    store = FeatureStore(3, budget_mb=1.0)
    store.put(["x"], np.ones((1, 3), np.float32))
    got, missing = store.gather(["x"], pad_to=4)
    assert missing == []
    g = np.asarray(got)
    assert g.shape == (4, 3)
    assert np.array_equal(g[0], np.ones(3, np.float32))
    assert np.isnan(g[1:]).all()                 # pad rows -> bin 0


def test_predict_by_id_zero_upload_parity():
    """predict_by_id == engine.predict on the same rows, with ZERO
    host→device feature bytes at steady state (the acceptance
    criterion, asserted via the transfer counters)."""
    import xgboost_tpu as xgb
    from xgboost_tpu.obs.metrics import predict_metrics
    from xgboost_tpu.serving import (FeatureStore, FeatureStoreMiss,
                                     PredictEngine, predict_by_id)
    bst, X, _ = _train(rounds=5, f=6)
    eng = PredictEngine(bst, min_bucket=8, max_bucket=32, warmup=True)
    store = FeatureStore(eng.num_feature, budget_mb=1.0)
    ids = [f"u{i}" for i in range(40)]           # > top bucket: chunks
    store.put(ids, X[:40])
    pm = predict_metrics()
    b0 = pm.transfer_bytes.value
    out = predict_by_id(eng, store, ids)
    assert pm.transfer_bytes.value == b0         # zero feature upload
    assert np.array_equal(out, eng.predict(X[:40]))
    assert np.array_equal(out, bst.predict(xgb.DMatrix(X[:40])))
    with pytest.raises(FeatureStoreMiss) as ei:
        predict_by_id(eng, store, ["u0", "ghost"])
    assert ei.value.missing == ["ghost"]
    # misses spread across CHUNKS are all reported at once (one
    # put-and-retry round trip, not one 404 per chunk) — and the miss
    # path feeds the hit/miss counters (it IS the dominant one)
    from xgboost_tpu.obs.metrics import featurestore_metrics
    fm = featurestore_metrics()
    h0, m0 = fm.hits.value, fm.misses.value
    with pytest.raises(FeatureStoreMiss) as ei:
        predict_by_id(eng, store, ["g1"] + ids + ["g2"])
    assert ei.value.missing == ["g1", "g2"]
    assert fm.misses.value - m0 == 2
    assert fm.hits.value - h0 == len(ids)


def test_predict_by_id_two_step_engine():
    """A two-step (non-fused) engine still serves resident entities
    with zero feature upload: quantize happens on device, eagerly."""
    from xgboost_tpu.obs.metrics import predict_metrics
    from xgboost_tpu.serving import (FeatureStore, PredictEngine,
                                     predict_by_id)
    bst, X, _ = _train(rounds=4, f=6)
    eng = PredictEngine(bst, min_bucket=8, max_bucket=32, warmup=True,
                        fused=False)
    store = FeatureStore(eng.num_feature, budget_mb=1.0)
    store.put(["a", "b"], X[:2])
    pm = predict_metrics()
    b0 = pm.transfer_bytes.value
    out = predict_by_id(eng, store, ["a", "b"])
    assert pm.transfer_bytes.value == b0
    assert np.array_equal(out, eng.predict(X[:2]))


# ------------------------------------------------------------- HTTP layer
def _post(base, path, obj):
    req = urllib.request.Request(base + path,
                                 data=json.dumps(obj).encode(),
                                 method="POST")
    try:
        r = urllib.request.urlopen(req)
        return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_predict_by_id_and_reload_rebinning(tmp_path):
    """The serving routes end to end: put → predict_by_id (parity,
    zero upload) → invalidate → 404; then a registry hot-reload with a
    DIFFERENT quantization (max_bin) rebins the SAME resident raw rows
    against the new model's cuts — predictions match the new booster
    bit for bit with still zero feature upload."""
    import xgboost_tpu as xgb
    from xgboost_tpu.obs.metrics import predict_metrics
    from xgboost_tpu.serving import run_server

    bst, X, _ = _train(rounds=5, f=6)
    path = str(tmp_path / "m.bin")
    bst.save_model(path)
    srv = run_server(path, port=0, min_bucket=4, max_bucket=64,
                     poll_sec=0, warmup=True, featurestore_mb=1.0,
                     quiet=True, block=False)
    try:
        base = f"http://{srv.host}:{srv.port}"
        ids = [f"u{i}" for i in range(10)]
        st, res = _post(base, "/featurestore/put",
                        {"ids": ids, "rows": X[:10].tolist()})
        assert st == 200 and res["stored"] == 10
        pm = predict_metrics()
        b0 = pm.transfer_bytes.value
        st, res = _post(base, "/predict_by_id", {"ids": ids})
        assert st == 200
        assert pm.transfer_bytes.value == b0     # zero-upload steady state
        assert np.allclose(res["predictions"], bst.predict(X[:10]),
                           rtol=0, atol=0)
        assert res["model_version"] == 1
        # body output_margin follows the query-string truthiness
        # contract: "0"/"false" disable, true/"1" enable
        st, rm = _post(base, "/predict_by_id",
                       {"ids": ids, "output_margin": "0"})
        assert rm["predictions"] == res["predictions"]
        st, rm = _post(base, "/predict_by_id",
                       {"ids": ids, "output_margin": True})
        assert np.allclose(rm["predictions"],
                           bst.predict(X[:10], output_margin=True),
                           rtol=0, atol=0)
        # misses name the absent ids
        st, res = _post(base, "/predict_by_id", {"ids": ["u0", "ghost"]})
        assert st == 404 and res["missing"] == ["ghost"]
        # hot-reload with different cuts: same resident rows, new bins
        bst2, _, _ = _train({"max_bin": 16}, rounds=6, f=6, seed=3)
        bst2.save_model(path)
        st, res = _post(base, "/-/reload", {})
        assert st == 200 and res["reloaded"]
        exp2 = bst2.predict(X[:10])              # its upload: pre-count
        b1 = pm.transfer_bytes.value
        st, res = _post(base, "/predict_by_id", {"ids": ids})
        assert st == 200 and res["model_version"] == 2
        assert np.allclose(res["predictions"], exp2, rtol=0, atol=0)
        assert pm.transfer_bytes.value == b1     # rebinning uploaded 0
        # invalidate drops residency
        st, res = _post(base, "/featurestore/invalidate", {"ids": ["u0"]})
        assert st == 200 and res["invalidated"] == 1
        st, res = _post(base, "/predict_by_id", {"ids": ["u0"]})
        assert st == 404
        # metrics render the new families
        m = urllib.request.urlopen(base + "/metrics").read().decode()
        for fam in ("xgbtpu_featurestore_hits_total",
                    "xgbtpu_featurestore_misses_total",
                    "xgbtpu_featurestore_evictions_total",
                    "xgbtpu_featurestore_resident_bytes",
                    "xgbtpu_predict_transfer_seconds",
                    "xgbtpu_predict_transfer_bytes_total"):
            assert fam in m, fam
        health = json.loads(
            urllib.request.urlopen(base + "/healthz").read())
        assert health["featurestore_rows"] == 9
    finally:
        srv.shutdown()


def test_fused_empty_input_matches_two_step(monkeypatch):
    """N=0 routes off the fused block pipeline (nothing to
    concatenate) and returns the same empty result as the baseline."""
    import xgboost_tpu as xgb
    bst, X, _ = _train(rounds=3, f=6)
    empty = np.zeros((0, 6), np.float32)
    out = bst.predict(empty)
    monkeypatch.setenv("XGBTPU_PREDICT_FUSED", "0")
    ref = bst.predict(xgb.DMatrix(empty))
    assert out.shape == ref.shape == (0,)


def test_featurestore_duplicate_ids_last_wins():
    """A repeated id in one put batch keeps its LAST row — the
    semantics of sequential puts — instead of feeding a repeated-index
    scatter (whose winner JAX leaves undefined)."""
    from xgboost_tpu.serving.featurestore import FeatureStore
    store = FeatureStore(3, budget_mb=1.0)
    X = np.arange(12, dtype=np.float32).reshape(4, 3)
    res = store.put(["a", "b", "a", "c"], X)
    assert res["stored"] == 3 and len(store) == 3
    got, missing = store.gather(["a", "b", "c"])
    assert missing == []
    assert np.array_equal(np.asarray(got), X[[2, 1, 3]])


def test_featurestore_failed_put_commits_nothing():
    """A device failure mid-put (upload/scatter) leaves membership and
    the slab untouched: no id may ever map to a row that was not
    written for it."""
    from xgboost_tpu.serving.featurestore import FeatureStore
    store = FeatureStore(2, budget_mb=2 * 2 * 4 / (1 << 20))
    assert store.capacity == 2
    X = np.arange(8, dtype=np.float32).reshape(4, 2)
    store.put(["a", "b"], X[:2])

    class _Boom:
        def asarray(self, *_a, **_k):
            raise RuntimeError("RESOURCE_EXHAUSTED (synthetic)")

    real = store._jnp
    store._jnp = _Boom()
    try:
        with pytest.raises(RuntimeError):
            store.put(["c"], X[2:3])             # would evict LRU "a"
    finally:
        store._jnp = real
    # the failed put evicted nothing and mapped nothing
    got, missing = store.gather(["a", "b"])
    assert missing == []
    assert np.array_equal(np.asarray(got), X[:2])
    _, missing = store.gather(["c"])
    assert missing == ["c"]


def test_http_reload_width_change_swaps_store(tmp_path):
    """A hot-reload to a model with a DIFFERENT feature count drops the
    store (resident rows are meaningless at the new width): by-id
    requests 404 as misses — never a shape-mismatched executable — and
    new-width puts are accepted."""
    import xgboost_tpu as xgb  # noqa: F401
    from xgboost_tpu.serving import run_server

    bst, X, _ = _train(rounds=3, f=6)
    path = str(tmp_path / "m.bin")
    bst.save_model(path)
    srv = run_server(path, port=0, min_bucket=4, max_bucket=16,
                     poll_sec=0, warmup=False, featurestore_mb=1.0,
                     quiet=True, block=False)
    try:
        base = f"http://{srv.host}:{srv.port}"
        st, _res = _post(base, "/featurestore/put",
                         {"ids": ["a"], "rows": X[:1].tolist()})
        assert st == 200
        bst8, X8, _ = _train(rounds=3, f=8, seed=2)
        bst8.save_model(path)
        st, res = _post(base, "/-/reload", {})
        assert st == 200 and res["reloaded"]
        st, res = _post(base, "/predict_by_id", {"ids": ["a"]})
        assert st == 404 and res["missing"] == ["a"]
        st, res = _post(base, "/featurestore/put",
                        {"ids": ["a"], "rows": X8[:1].tolist()})
        assert st == 200 and res["num_feature"] == 8
        st, res = _post(base, "/predict_by_id", {"ids": ["a"]})
        assert st == 200
        assert np.allclose(res["predictions"], bst8.predict(X8[:1]),
                           rtol=0, atol=0)
    finally:
        srv.shutdown()


def test_sparse_ndarray_keeps_host_binned_path():
    """The direct-buffer shortcut is an upload optimization, not a
    routing override: a mostly-NaN ndarray stays on the O(nnz)
    host-binning path (small-int bin upload), never ships the full f32
    matrix."""
    import xgboost_tpu as xgb
    from xgboost_tpu.binning import bin_matrix
    from xgboost_tpu.obs.metrics import predict_metrics
    bst, X, _ = _train(rounds=3, f=6)
    Xs = np.full((300, 6), np.nan, np.float32)
    Xs[:, 0] = X[:300, 0]                        # ~17%: below the gate
    ref = bst.predict(xgb.DMatrix(Xs))
    pm = predict_metrics()
    b0 = pm.transfer_bytes.value
    out = bst.predict(np.ascontiguousarray(Xs))
    delta = pm.transfer_bytes.value - b0
    binned = bin_matrix(xgb.DMatrix(Xs), bst.gbtree.cuts)
    assert delta == binned.nbytes                # bins, not f32 rows
    assert np.array_equal(ref, out)


def test_http_featurestore_put_device_failure_500(tmp_path):
    """A device failure inside put surfaces as a 500 JSON error (and
    commits nothing) instead of a dropped connection."""
    import xgboost_tpu as xgb  # noqa: F401
    from xgboost_tpu.serving import run_server
    bst, X, _ = _train(rounds=2, f=6)
    path = str(tmp_path / "m.bin")
    bst.save_model(path)
    srv = run_server(path, port=0, min_bucket=4, max_bucket=16,
                     poll_sec=0, warmup=False, featurestore_mb=1.0,
                     quiet=True, block=False)
    try:
        base = f"http://{srv.host}:{srv.port}"

        class _Boom:
            def asarray(self, *_a, **_k):
                raise RuntimeError("RESOURCE_EXHAUSTED (synthetic)")

        real = srv.featurestore._jnp
        srv.featurestore._jnp = _Boom()
        try:
            st, res = _post(base, "/featurestore/put",
                            {"ids": ["a"], "rows": X[:1].tolist()})
        finally:
            srv.featurestore._jnp = real
        assert st == 500 and "RESOURCE_EXHAUSTED" in res["error"]
        assert len(srv.featurestore) == 0        # committed nothing
        # the mutating store routes pass the drain admission gate:
        # a draining server must not accept new device uploads
        srv.state = "draining"
        try:
            st, res = _post(base, "/featurestore/put",
                            {"ids": ["a"], "rows": X[:1].tolist()})
            assert st == 503 and "draining" in res["error"]
            st, res = _post(base, "/featurestore/invalidate",
                            {"all": True})
            assert st == 503
        finally:
            srv.state = "serving"
    finally:
        srv.shutdown()


def test_http_featurestore_disabled_404(tmp_path):
    import xgboost_tpu as xgb  # noqa: F401
    from xgboost_tpu.serving import run_server
    bst, X, _ = _train(rounds=2, f=6)
    path = str(tmp_path / "m.bin")
    bst.save_model(path)
    srv = run_server(path, port=0, min_bucket=4, max_bucket=16,
                     poll_sec=0, warmup=False, quiet=True, block=False)
    try:
        base = f"http://{srv.host}:{srv.port}"
        st, res = _post(base, "/predict_by_id", {"ids": ["a"]})
        assert st == 404 and "disabled" in res["error"]
        st, res = _post(base, "/featurestore/put",
                        {"ids": ["a"], "rows": [[0.0] * 6]})
        assert st == 404
    finally:
        srv.shutdown()

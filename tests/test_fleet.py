"""Fleet subsystem tests (xgboost_tpu.fleet; SERVING.md fleet section).

Acceptance criteria covered here (ISSUE 7):
(a) router /predict responses are BIT-identical to a direct replica
    call (pure passthrough dispatch);
(b) consistent-hash stickiness: an entity id maps to one replica
    across requests, so feature-store residency concentrates — and
    membership churn only remaps the changed replica's keys;
(c) circuit breaker: consecutive failures trip it open, a half-open
    probe after cooldown closes it again, and client traffic never
    fails while it does (retry-once on a healthy replica);
(d) drain -> leaves rotation -> re-register (the tracker `recover`
    path), and a heartbeat-loss chaos fault decays the lease;
(e) canary rollout gates on canary /metrics and ROLLS BACK when the
    canary is chaos-killed mid-soak, with the fleet still serving the
    prior content hash;
(f) the router's global in-flight budget sheds with 503.
"""

import hashlib
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.fleet import FleetRouter, HashRing, scrape_samples
from xgboost_tpu.serving import run_server


def _train(seed=0, rounds=3, **params):
    rng = np.random.RandomState(seed)
    X = rng.rand(300, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    p = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.4,
         "silent": 1, "seed": seed, **params}
    return xgb.train(p, xgb.DMatrix(X, label=y), rounds), X


@pytest.fixture(scope="module")
def models(tmp_path_factory):
    d = tmp_path_factory.mktemp("fleet")
    bst_a, X = _train()
    bst_b, _ = _train(seed=7, rounds=4, max_depth=2)
    pa, pb = str(d / "model_a.bin"), str(d / "model_b.bin")
    bst_a.save_model(pa)
    bst_b.save_model(pb)
    return bst_a, bst_b, X, pa, pb


def _file_hash(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _replica(model_path, router_url, rid, fs_mb=0.0):
    return run_server(model_path, port=0, min_bucket=8, max_bucket=32,
                      max_wait_ms=1.0, poll_sec=0, warmup=False,
                      featurestore_mb=fs_mb, quiet=True, block=False,
                      router_url=router_url, replica_id=rid)


def _post(url, payload=None, data=None, headers=None):
    """POST -> (status, parsed-json, raw-bytes, response-headers)."""
    body = (json.dumps(payload).encode() if payload is not None
            else (data or b""))
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            raw = r.read()
            return r.status, json.loads(raw), raw, dict(r.headers)
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            return e.code, json.loads(raw), raw, dict(e.headers)
        except ValueError:
            return e.code, {}, raw, dict(e.headers)


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def _csv(rows):
    return "\n".join(",".join(f"{v:.6f}" for v in row)
                     for row in rows).encode()


# ---------------------------------------------------------------- ring
def test_hash_ring_sticky_and_minimal_remap():
    ring = HashRing(vnodes=64)
    ring.rebuild(["r0", "r1", "r2"])
    keys = [f"user-{i}" for i in range(400)]
    owners = {k: ring.route(k, {"r0", "r1", "r2"}) for k in keys}
    # deterministic: same key -> same owner, every replica owns some
    assert owners == {k: ring.route(k, {"r0", "r1", "r2"}) for k in keys}
    assert set(owners.values()) == {"r0", "r1", "r2"}
    # dropping r1 from ELIGIBILITY remaps only r1's keys (ring walk
    # skips it); everyone else stays put — the failover property
    for k in keys:
        moved = ring.route(k, {"r0", "r2"})
        if owners[k] != "r1":
            assert moved == owners[k], f"{k} moved needlessly"
        else:
            assert moved in ("r0", "r2")
    # removing r1 from the RING entirely keeps survivors' keys put too
    ring.rebuild(["r0", "r2"])
    for k in keys:
        if owners[k] != "r1":
            assert ring.route(k, {"r0", "r2"}) == owners[k]


def test_scrape_samples_parses_exposition():
    text = ("# HELP x_total help\n# TYPE x_total counter\n"
            "x_total 41\n"
            'labeled_total{replica="r1"} 7\n'
            "x_p99 0.25\n"
            "x_small 9.5e-05\n"       # repr() e-notation below 1e-4
            "x_neg -2.5\n")
    s = scrape_samples(text)
    assert s["x_total"] == 41.0 and s["x_p99"] == 0.25
    assert s["x_small"] == 9.5e-05 and s["x_neg"] == -2.5
    assert "labeled_total" not in s


# ------------------------------------------------------- routing parity
def test_router_predict_bit_identical_and_traced(models, tmp_path):
    """(a) router passthrough: byte-identical body to a direct replica
    call, X-Request-Id echoed end to end."""
    bst_a, _, X, pa, _ = models
    rt = FleetRouter(port=0, hc_sec=0.5, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    srv = _replica(pa, base, "r1")
    try:
        assert _get(base + "/fleet/members")["in_rotation"] == 1
        body = _csv(np.round(X[:5], 6))
        st_r, js_r, raw_r, hdr_r = _post(
            base + "/predict", data=body,
            headers={"X-Request-Id": "trace-123"})
        st_d, js_d, raw_d, _ = _post(
            f"http://{srv.host}:{srv.port}/predict", data=body)
        assert st_r == st_d == 200
        assert raw_r == raw_d, "router response differs from direct call"
        assert hdr_r["X-Request-Id"] == "trace-123"
        # predictions match the model bit for bit through json
        ref = bst_a.predict(xgb.DMatrix(np.round(X[:5], 6)))
        assert np.array_equal(
            np.asarray(js_r["predictions"], np.float32), ref)
        # output_margin query string passes through
        st_m, js_m, _, _ = _post(base + "/predict?output_margin=1",
                                 data=body)
        refm = bst_a.predict(xgb.DMatrix(np.round(X[:5], 6)),
                             output_margin=True)
        assert st_m == 200
        assert np.array_equal(
            np.asarray(js_m["predictions"], np.float32), refm)
        # replica /healthz carries the content hash of what it serves
        h = _get(f"http://{srv.host}:{srv.port}/healthz")
        assert h["model_hash"] == _file_hash(pa)
    finally:
        srv.shutdown()
        rt.shutdown()


# ----------------------------------------------- consistent-hash by id
def test_predict_by_id_consistent_hash_stickiness(models):
    """(b) puts and predicts for one entity land on ONE replica:
    residency concentrates, and by-id traffic via the router is
    bit-identical to the engine on the same rows."""
    bst_a, _, X, pa, _ = models
    rt = FleetRouter(port=0, hc_sec=0.5, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    s1 = _replica(pa, base, "r1", fs_mb=4.0)
    s2 = _replica(pa, base, "r2", fs_mb=4.0)
    try:
        assert _get(base + "/fleet/members")["in_rotation"] == 2
        ids = [f"user-{i}" for i in range(12)]
        rows = np.round(X[:12], 6).astype(np.float32)
        st, js, _, _ = _post(base + "/featurestore/put",
                             {"ids": ids, "rows": rows.tolist()})
        assert st == 200, js
        # residency split across replicas, nothing duplicated
        n1 = _get(f"http://{s1.host}:{s1.port}/healthz")["featurestore_rows"]
        n2 = _get(f"http://{s2.host}:{s2.port}/healthz")["featurestore_rows"]
        assert n1 + n2 == 12 and n1 > 0 and n2 > 0
        # by-id predictions through the router match the model exactly
        st, js, _, _ = _post(base + "/predict_by_id", {"ids": ids})
        assert st == 200 and js["rows"] == 12
        ref = bst_a.predict(xgb.DMatrix(rows))
        assert np.array_equal(np.asarray(js["predictions"], np.float32),
                              ref)
        # residency is EXCLUSIVE: each id lives on exactly one replica
        # (the put split followed the same ring the predicts follow)
        for eid in ids:
            on_1 = not s1.featurestore.missing([eid])
            on_2 = not s2.featurestore.missing([eid])
            assert on_1 != on_2, f"{eid} resident on {on_1 + on_2} stores"
        # stickiness: repeated single-id requests keep succeeding —
        # only the one replica holding the row can serve them, so a
        # routing flap would surface as a 404 miss
        for _i in range(3):
            st, js1, _, _ = _post(base + "/predict_by_id",
                                  {"ids": [ids[0]]})
            assert st == 200
            assert np.float32(js1["predictions"][0]) == ref[0]
        # absent ids 404 with the missing list (merged across replicas)
        st, js, _, _ = _post(base + "/predict_by_id",
                             {"ids": ["ghost-1", ids[0], "ghost-2"]})
        assert st == 404
        assert set(js["missing"]) == {"ghost-1", "ghost-2"}
    finally:
        s1.shutdown()
        s2.shutdown()
        rt.shutdown()


# -------------------------------------------------------- stub replica
class _Stub:
    """Minimal fake replica: /healthz always serving; /predict fails
    (500) while ``fail`` is set, else answers after ``delay``."""

    def __init__(self):
        self.fail = False
        self.delay = 0.0
        self.hits = 0
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def log_message(self, *a):
                pass

            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._send(200, {"status": "ok", "state": "serving",
                                 "model_hash": "stub"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                stub.hits += 1
                if stub.fail:
                    self._send(500, {"error": "stub failure"})
                    return
                if stub.delay:
                    time.sleep(stub.delay)
                self._send(200, {"predictions": [0.5], "rows": 1,
                                 "model_version": 1})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _register_stub(base, rid, stub):
    st, js, _, _ = _post(base + "/fleet/register",
                         {"replica_id": rid, "url": stub.url})
    assert st == 200, js


# ------------------------------------------------------------- breaker
def test_breaker_trip_half_open_recovery():
    """(c) consecutive failures trip the breaker; traffic keeps
    succeeding via retry on the healthy replica; after cooldown one
    half-open probe closes it again."""
    rt = FleetRouter(port=0, hc_sec=0, breaker_failures=2,
                     breaker_cooldown_sec=0.5, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    bad, good = _Stub(), _Stub()
    bad.fail = True
    try:
        _register_stub(base, "bad", bad)
        _register_stub(base, "good", good)
        # every request succeeds (retry-once covers the bad replica)
        for _ in range(6):
            st, js, _, _ = _post(base + "/predict", data=b"0.5")
            assert st == 200, js
        members = {m["replica_id"]: m
                   for m in _get(base + "/fleet/members")["replicas"]}
        assert members["bad"]["breaker"] == "open"
        assert members["good"]["breaker"] == "closed"
        bad_hits_at_trip = bad.hits
        assert bad_hits_at_trip >= 2
        # while OPEN no traffic reaches the bad replica
        for _ in range(4):
            st, _, _, _ = _post(base + "/predict", data=b"0.5")
            assert st == 200
        assert bad.hits == bad_hits_at_trip
        # heal the replica, wait out the cooldown: the half-open probe
        # closes the breaker and traffic flows there again
        bad.fail = False
        time.sleep(0.6)
        for _ in range(6):
            st, _, _, _ = _post(base + "/predict", data=b"0.5")
            assert st == 200
        members = {m["replica_id"]: m
                   for m in _get(base + "/fleet/members")["replicas"]}
        assert members["bad"]["breaker"] == "closed"
        assert bad.hits > bad_hits_at_trip, "no traffic after recovery"
        # a failed probe would have re-opened: verify metrics recorded
        # the trip
        mtext = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "xgbtpu_fleet_breaker_trips_total" in mtext
    finally:
        bad.close()
        good.close()
        rt.shutdown()


# ------------------------------------------------- drain / re-register
def test_drain_leaves_rotation_then_reregister(models):
    """(d) a draining replica deregisters (leaves rotation BEFORE
    503ing); a restart re-registers under the same id — recover."""
    _, _, X, pa, _ = models
    rt = FleetRouter(port=0, hc_sec=0.3, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    s1 = _replica(pa, base, "r1")
    s2 = _replica(pa, base, "r2")
    try:
        assert _get(base + "/fleet/members")["in_rotation"] == 2
        body = _csv(X[:2])
        s1.drain(grace=5.0)  # deregisters via the lease client
        desc = _get(base + "/fleet/members")
        assert desc["in_rotation"] == 1
        assert [m["replica_id"] for m in desc["replicas"]
                if m["in_rotation"]] == ["r2"]
        # fleet keeps serving through the survivor
        for _ in range(3):
            st, _, _, _ = _post(base + "/predict", data=body)
            assert st == 200
        # restart under the SAME id: back in rotation (recover path)
        s1b = _replica(pa, base, "r1")
        try:
            desc = _get(base + "/fleet/members")
            assert desc["in_rotation"] == 2
            r1 = [m for m in desc["replicas"]
                  if m["replica_id"] == "r1"][0]
            assert r1["in_rotation"] and r1["url"].endswith(
                str(s1b.port))
            # traffic reaches the rejoined fleet
            st, _, _, _ = _post(base + "/predict", data=body)
            assert st == 200
        finally:
            s1b.shutdown()
    finally:
        s2.shutdown()
        rt.shutdown()


def test_heartbeat_loss_decays_lease_then_recovers(models):
    """(d) chaos ``heartbeat_loss``: missed renewals expire the lease
    (out of rotation, no process death); the next successful heartbeat
    re-registers — the router never stopped knowing how to take it
    back."""
    from xgboost_tpu.reliability import faults
    _, _, X, pa, _ = models
    rt = FleetRouter(port=0, hc_sec=0, lease_sec=0.6, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    s1 = _replica(pa, base, "r1")
    try:
        assert _get(base + "/fleet/members")["in_rotation"] == 1
        faults.inject("heartbeat_loss", path_sub="r1", times=50)
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline:
            if _get(base + "/fleet/members")["in_rotation"] == 0:
                break
            time.sleep(0.1)
        assert _get(base + "/fleet/members")["in_rotation"] == 0, \
            "lease survived lost heartbeats"
        assert s1.lease_client.heartbeats_skipped > 0
        faults.clear_faults()
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline:
            if _get(base + "/fleet/members")["in_rotation"] == 1:
                break
            time.sleep(0.1)
        assert _get(base + "/fleet/members")["in_rotation"] == 1, \
            "replica did not recover after heartbeats resumed"
    finally:
        faults.clear_faults()
        s1.shutdown()
        rt.shutdown()


# ------------------------------------------------------------ rollout
def test_canary_rollout_success_then_fleet_rollback(models):
    """(e) happy path: canary gate passes, the fleet converges on the
    new content hash (verified via each replica's OWN /healthz), and
    the one-command rollback restores the previous hash fleet-wide."""
    bst_a, bst_b, X, pa, pb = models
    rt = FleetRouter(port=0, hc_sec=0.3, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    import shutil
    import tempfile
    d = tempfile.mkdtemp(prefix="xgbtpu_fleetroll_")
    m1, m2 = f"{d}/r1.bin", f"{d}/r2.bin"
    shutil.copyfile(pa, m1)
    shutil.copyfile(pa, m2)
    hash_a, hash_b = _file_hash(pa), _file_hash(pb)
    s1 = _replica(m1, base, "r1")
    s2 = _replica(m2, base, "r2")
    try:
        assert _get(base + "/fleet/members")["in_rotation"] == 2
        st, js, _, _ = _post(base + "/fleet/rollout",
                             {"model_path": pb, "canaries": 1,
                              "soak_sec": 0.2,
                              "gate_error_rate": 0.5,
                              "gate_p99_ms": 10_000.0})
        assert st == 200 and js["status"] == "ok", js
        assert js["model_hash"] == hash_b
        assert js["canaries"] == ["r1"]
        for s in (s1, s2):
            h = _get(f"http://{s.host}:{s.port}/healthz")
            assert h["model_hash"] == hash_b, "replica not on new model"
        # responses now come from model B
        body = _csv(np.round(X[:4], 6))
        st, js, _, _ = _post(base + "/predict", data=body)
        ref_b = bst_b.predict(xgb.DMatrix(np.round(X[:4], 6)))
        assert np.array_equal(np.asarray(js["predictions"], np.float32),
                              ref_b)
        # one-command instant rollback, fleet-wide
        st, js, _, _ = _post(base + "/fleet/rollback")
        assert st == 200 and js["status"] == "rolled_back"
        for s in (s1, s2):
            h = _get(f"http://{s.host}:{s.port}/healthz")
            assert h["model_hash"] == hash_a, "rollback missed a replica"
        st, js, _, _ = _post(base + "/predict", data=body)
        ref_a = bst_a.predict(xgb.DMatrix(np.round(X[:4], 6)))
        assert np.array_equal(np.asarray(js["predictions"], np.float32),
                              ref_a)
        # backups refresh PER ROLLOUT: after rolling B then A, a
        # rollback must restore the PREVIOUS version (B) — files
        # included — not the pre-first-rollout bytes (a stale backup
        # would split engines from files here)
        roll_args = {"canaries": 1, "soak_sec": 0.2,
                     "gate_error_rate": 0.5, "gate_p99_ms": 10_000.0}
        st, js, _, _ = _post(base + "/fleet/rollout",
                             {"model_path": pb, **roll_args})
        assert st == 200 and js["status"] == "ok", js
        st, js, _, _ = _post(base + "/fleet/rollout",
                             {"model_path": pa, **roll_args})
        assert st == 200 and js["status"] == "ok", js
        st, js, _, _ = _post(base + "/fleet/rollback")
        assert st == 200
        for s, path in ((s1, m1), (s2, m2)):
            h = _get(f"http://{s.host}:{s.port}/healthz")
            assert h["model_hash"] == hash_b, \
                "rollback restored the wrong (stale) version"
            assert _file_hash(path) == hash_b, \
                "rollback restored stale file bytes"
    finally:
        s1.shutdown()
        s2.shutdown()
        rt.shutdown()


def test_canary_rollout_rolls_back_when_canary_killed(models):
    """(e) the chaos case: the canary dies mid-soak, the gate cannot
    observe it -> the rollout rolls back and the SURVIVING fleet still
    serves the prior hash."""
    bst_a, _, X, pa, pb = models
    rt = FleetRouter(port=0, hc_sec=0.2, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    import shutil
    import tempfile
    d = tempfile.mkdtemp(prefix="xgbtpu_fleetkill_")
    m1, m2 = f"{d}/r1.bin", f"{d}/r2.bin"
    shutil.copyfile(pa, m1)
    shutil.copyfile(pa, m2)
    hash_a = _file_hash(pa)
    s1 = _replica(m1, base, "r1")  # r1 sorts first -> the canary
    s2 = _replica(m2, base, "r2")
    try:
        assert _get(base + "/fleet/members")["in_rotation"] == 2
        killer = threading.Timer(0.4, s1.shutdown)  # dies mid-soak
        killer.start()
        st, js, _, _ = _post(base + "/fleet/rollout",
                             {"model_path": pb, "canaries": 1,
                              "soak_sec": 1.5,
                              "gate_error_rate": 0.5,
                              "gate_p99_ms": 10_000.0})
        killer.join()
        assert st == 500 and js["status"] == "rolled_back", js
        assert "unreachable" in js["reason"]
        # the untouched replica still serves the prior hash; the fleet
        # still answers predictions (health check dropped the corpse)
        h = _get(f"http://{s2.host}:{s2.port}/healthz")
        assert h["model_hash"] == hash_a
        time.sleep(0.5)  # let the health checker notice the kill
        body = _csv(np.round(X[:3], 6))
        for _ in range(3):
            st, js, _, _ = _post(base + "/predict", data=body)
            assert st == 200
        ref_a = bst_a.predict(xgb.DMatrix(np.round(X[:3], 6)))
        assert np.array_equal(np.asarray(js["predictions"], np.float32),
                              ref_a)
        # the rollback restored the canary's model FILE too: a restart
        # of r1 comes back serving hash A, not the failed push
        assert _file_hash(m1) == hash_a
    finally:
        s2.shutdown()
        rt.shutdown()


# ----------------------------------------------- latency-aware ejection
def test_latency_ejection_routes_around_wedged_replica():
    """ISSUE 10 tentpole (3): a slow-but-alive replica — every request
    SUCCEEDS, so the failure-count breaker never sees it — is ejected
    on its latency EWMA (k × fleet median), traffic routes around it
    with zero failures, and a fast post-cooldown probe readmits it."""
    rt = FleetRouter(port=0, hc_sec=0, slow_eject_factor=3.0,
                     slow_eject_cooldown_sec=0.8, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    slow, f1, f2 = _Stub(), _Stub(), _Stub()
    slow.delay = 0.12  # wedged-but-alive: answers, late
    try:
        # "a-slow" sorts first, so least-loaded ties prefer the wedge
        _register_stub(base, "a-slow", slow)
        _register_stub(base, "b-fast", f1)
        _register_stub(base, "c-fast", f2)
        codes = []
        lock = threading.Lock()

        def fire(n):
            for _ in range(n):
                st, _, _, _ = _post(base + "/predict", data=b"0.5")
                with lock:
                    codes.append(st)

        # concurrent traffic until the ejection fires (scheduling on a
        # loaded 1-core host decides how fast the wedge accumulates
        # its EWMA samples; the CONTRACT is that it ejects, not when)
        members = {}
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            ts = [threading.Thread(target=fire, args=(6,))
                  for _ in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            members = {m["replica_id"]: m for m in
                       _get(base + "/fleet/members")["replicas"]}
            if members["a-slow"]["ejected"]:
                break
        assert set(codes) == {200}, "a slow replica cost client errors"
        assert members["a-slow"]["ejected"] is True
        # the breaker never saw it — this is the DISTINCT state
        assert members["a-slow"]["breaker"] == "closed"
        assert members["a-slow"]["latency_ewma_ms"] > 50
        assert members["b-fast"]["ejected"] is False
        samples = scrape_samples(urllib.request.urlopen(
            base + "/metrics", timeout=10).read().decode())
        assert samples["xgbtpu_fleet_slow_ejections_total"] >= 1
        # while ejected the wedge takes no REGULAR traffic — at most
        # one readmission probe may slip in if the burst already aged
        # past the cooldown (the probe is ejection working as designed,
        # not a dispatch leak)
        hits_at_eject = slow.hits
        fire(8)
        assert slow.hits <= hits_at_eject + 1, \
            "ejected replica kept receiving regular dispatches"
        # heal it; after the cooldown ONE probe decides readmission
        slow.delay = 0.0
        time.sleep(0.9)
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline:
            fire(4)
            members = {m["replica_id"]: m for m in
                       _get(base + "/fleet/members")["replicas"]}
            if not members["a-slow"]["ejected"]:
                break
        assert members["a-slow"]["ejected"] is False, \
            "healed replica never readmitted"
        assert slow.hits > hits_at_eject
        assert set(codes) == {200}
    finally:
        slow.close()
        f1.close()
        f2.close()
        rt.shutdown()


def test_ejection_state_machine_edge_cases():
    """Direct-state checks on the ejection machinery: (a) lease-dead
    ghosts are excluded from the peer-median comparator; (b) a recover
    re-registration resets ejection state like it resets the breaker;
    (c) only the dispatch that was GRANTED the readmission probe can
    resolve it (thread-token attribution)."""
    import time as _t

    from xgboost_tpu.fleet.membership import Membership
    m = Membership(lease_sec=30.0, slow_eject_factor=3.0)
    for rid in ("r1", "r2", "r3"):
        m.register(rid, f"http://x/{rid}")
    r1, r2, r3 = (m.get(r) for r in ("r1", "r2", "r3"))
    # (a) ghost exclusion: r3 dies without deregistering, carrying a
    # huge wedged-era EWMA — the live pair's comparator must not see it
    r1.lat_ewma, r1.lat_samples = 0.002, 20
    r2.lat_ewma, r2.lat_samples = 0.002, 20
    r3.lat_ewma, r3.lat_samples = 0.600, 20
    r3.lease_deadline = _t.monotonic() - 1.0  # lease-dead ghost
    with m._lock:
        assert m._peer_median_lat_locked(r1) == pytest.approx(0.002)
    # (b) recover reset: an ejected, wedged-history replica restarts
    # and re-registers under its old id — clean slate
    r2.ejected = True
    r2.ejected_at = _t.monotonic()
    r2.lat_ewma, r2.lat_samples = 0.600, 50
    assert m.register("r2", "http://x/r2b")["recovered"] is True
    r2 = m.get("r2")
    assert r2.ejected is False and r2.lat_samples == 0
    assert r2.lat_ewma == 0.0
    # (c) probe attribution: grant the probe on this thread, then a
    # release from ANOTHER thread (a fast entity-id hop) must neither
    # resolve the probe nor readmit the replica.  (r2 gets fresh fast
    # samples first: with no live sampled peer there is no comparator
    # and a successful probe readmits unconditionally.)
    r2.lat_ewma, r2.lat_samples = 0.002, 20
    r1.ejected = True
    r1.ejected_at = _t.monotonic() - 60.0  # cooldown long past
    rep = m.acquire()  # grants r1's readmission probe to THIS thread
    assert rep is r1 and r1.eject_probe_inflight
    other = m.acquire_specific("r1")  # entity-id hop, not gated
    assert other is r1
    done = []
    t = threading.Thread(
        target=lambda: done.append(m.release(r1, ok=True, latency=0.001)))
    t.start()
    t.join()
    assert r1.ejected is True, "foreign release resolved the probe"
    assert r1.eject_probe_inflight, "foreign release freed the slot"
    # the GRANTED thread's slow probe outcome keeps it ejected
    m.release(r1, ok=True, latency=0.5)
    assert r1.ejected is True and not r1.eject_probe_inflight


def test_slow_replica_fault_kind_delays_predicts(models):
    """The `slow_replica` chaos kind (reliability/faults.py) wedges a
    real replica's predict path — keyed on its fleet replica id, lease
    and health untouched — so the ejection machinery is chaos-testable
    end to end (tools/chaos_loop.py --fleet --slow)."""
    from xgboost_tpu.reliability import faults
    _, _, X, pa, _ = models
    rt = FleetRouter(port=0, hc_sec=0.3, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    srv = _replica(pa, base, "r-wedge")
    try:
        assert _get(base + "/fleet/members")["in_rotation"] == 1
        body = _csv(X[:2])
        # warm the replica first: the baseline must not include the
        # cold-start bucket compile (which alone can exceed the wedge)
        for _ in range(3):
            st, _, _, _ = _post(base + "/predict", data=body)
            assert st == 200
        t0 = time.perf_counter()
        st, _, _, _ = _post(base + "/predict", data=body)
        assert st == 200
        fast = time.perf_counter() - t0
        assert fast < 0.4, f"warm baseline too slow ({fast:.2f}s)"
        faults.inject("slow_replica", 0.4, path_sub="r-wedge", times=1)
        t0 = time.perf_counter()
        st, _, _, _ = _post(base + "/predict", data=body)
        wedged = time.perf_counter() - t0
        assert st == 200, "the wedge must delay, never fail"
        assert wedged >= 0.4 > fast
        # the fault disarmed after `times`: back to fast
        t0 = time.perf_counter()
        st, _, _, _ = _post(base + "/predict", data=body)
        assert st == 200
        assert time.perf_counter() - t0 < 0.4
    finally:
        faults.clear_faults()
        srv.shutdown()
        rt.shutdown()


# ----------------------------------------------------------- load shed
def test_router_inflight_budget_sheds_503():
    """(f) admission control: concurrent requests past the global
    budget shed with 503 immediately; admitted ones all succeed."""
    rt = FleetRouter(port=0, hc_sec=0, inflight_budget=2,
                     quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    stub = _Stub()
    stub.delay = 0.4
    try:
        _register_stub(base, "slow", stub)
        results = []
        lock = threading.Lock()

        def fire():
            st, js, _, _ = _post(base + "/predict", data=b"0.5")
            with lock:
                results.append((st, js))

        ts = [threading.Thread(target=fire) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        codes = [st for st, _ in results]
        assert codes.count(200) >= 1
        assert codes.count(503) >= 1, f"no shedding: {codes}"
        assert set(codes) <= {200, 503}
        for st, js in results:
            if st == 503:
                assert js.get("shed") is True
        def scrape():
            return scrape_samples(urllib.request.urlopen(
                base + "/metrics", timeout=10).read().decode())

        shed = scrape()
        assert shed["xgbtpu_fleet_shed_total"] == codes.count(503)
        # settle poll: a handler thread sends its response BEFORE its
        # finally-block exit_request runs, so the gauge can trail the
        # client's join by a scheduling quantum
        deadline = time.perf_counter() + 5.0
        while (shed["xgbtpu_fleet_inflight"]
               and time.perf_counter() < deadline):
            time.sleep(0.05)
            shed = scrape()
        assert shed["xgbtpu_fleet_inflight"] == 0
    finally:
        stub.close()
        rt.shutdown()


# ------------------------------------------------------ registry hash
def test_registry_describe_and_hash_follow_rollback(models, tmp_path):
    """Satellite: ModelRegistry.describe()/content_hash name what the
    live engine ACTUALLY serves — through reloads AND rollbacks."""
    from xgboost_tpu.serving import ModelRegistry
    bst_a, bst_b, _, pa, pb = models
    path = str(tmp_path / "m.bin")
    import shutil
    shutil.copyfile(pa, path)
    hash_a, hash_b = _file_hash(pa), _file_hash(pb)
    reg = ModelRegistry(path, warmup=False, poll_sec=0,
                        min_bucket=8, max_bucket=32)
    assert reg.content_hash == hash_a
    d = reg.describe()
    assert d["model_hash"] == hash_a and d["model_version"] == 1
    assert d["engine"]["num_feature"] == 6
    shutil.copyfile(pb, path)
    assert reg.check_reload() is True
    assert reg.content_hash == hash_b
    # rollback: the hash follows the ENGINE, not the on-disk file
    assert reg.rollback() is True
    assert reg.content_hash == hash_a
    assert reg.describe()["model_hash"] == hash_a
    assert reg.rollback() is True  # toggles back
    assert reg.content_hash == hash_b


def test_cli_usage_lists_fleet_params(capsys):
    from xgboost_tpu.cli import main as cli_main
    assert cli_main([]) == 0
    out = capsys.readouterr().out
    assert "fleet_router" in out
    for name in ("fleet_port", "fleet_lease_sec", "fleet_inflight",
                 "fleet_breaker_failures", "serve_router_url"):
        assert name in out, f"{name} missing from CLI usage"

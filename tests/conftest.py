"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the analog of the reference's local multi-process test harness
(``subtree/rabit/tracker/rabit_demo.py``): distributed code paths are
exercised on one host by forcing 8 virtual CPU devices.  Must run before
jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may point at TPU
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon TPU plugin re-registers itself over JAX_PLATFORMS at import time;
# an explicit config update is the reliable override.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration drives (demo suite)")


@pytest.fixture
def mesh8():
    """The 8-virtual-device data-parallel mesh."""
    from xgboost_tpu.parallel.mesh import data_parallel_mesh
    return data_parallel_mesh(8)


@pytest.fixture
def recompile_guard():
    """XLA backend-compile budget assertions (ANALYSIS.md): the
    generalized form of the serving zero-steady-state-recompile test —
    ``with recompile_guard.expect(0): hot_path()`` fails if the region
    compiles anything."""
    from xgboost_tpu.analysis.runtime import RecompileGuard
    return RecompileGuard()


@pytest.fixture
def lock_race_checker():
    """Instrumented-lock race observer (ANALYSIS.md): ``instrument`` an
    object under concurrency stress, then ``assert_clean()``.  Teardown
    asserts automatically so a test cannot forget to look."""
    from xgboost_tpu.analysis.runtime import LockRaceChecker
    checker = LockRaceChecker()
    yield checker
    checker.assert_clean()


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Free compiled executables between test MODULES: a single pytest
    process accumulates every jit executable of ~190 tests, and the XLA
    CPU compiler has been seen segfaulting late in the run under that
    memory pressure.  Cross-module cache reuse is minimal (each module
    compiles its own shapes), so this costs little."""
    yield
    jax.clear_caches()

"""Objective coverage: multiclass, gblinear, LambdaRank (reference demo
configs: multiclass_classification, rank, generalized_linear_model)."""

import numpy as np
import pytest

import xgboost_tpu as xgb


def make_multiclass(n=1200, k=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 6).astype(np.float32)
    logits = X[:, :k] + 0.3 * rng.randn(n, k)
    y = np.argmax(logits, axis=1).astype(np.float32)
    return X, y


def test_multi_softmax():
    X, y = make_multiclass()
    dtrain = xgb.DMatrix(X[:900], label=y[:900])
    dtest = xgb.DMatrix(X[900:], label=y[900:])
    params = {"objective": "multi:softmax", "num_class": 4, "max_depth": 4,
              "eta": 0.3}
    res = {}
    bst = xgb.train(params, dtrain, 15, evals=[(dtest, "test")],
                    evals_result=res, verbose_eval=False)
    assert res["test-merror"][-1] < 0.25
    preds = bst.predict(dtest)
    assert preds.shape == (300,)
    assert set(np.unique(preds)) <= {0.0, 1.0, 2.0, 3.0}
    err = np.mean(preds != y[900:])
    assert abs(err - res["test-merror"][-1]) < 1e-6


def test_multi_softprob():
    X, y = make_multiclass()
    dtrain = xgb.DMatrix(X, label=y)
    params = {"objective": "multi:softprob", "num_class": 4, "max_depth": 3}
    bst = xgb.train(params, dtrain, 5, verbose_eval=False)
    preds = bst.predict(dtrain)
    assert preds.shape == (1200, 4)
    np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-5)
    # mlogloss metric runs on multiclass output
    line = bst.eval_set([(dtrain, "train")], 0)
    assert "train-merror" in line


def test_multiclass_mlogloss_decreases():
    X, y = make_multiclass()
    dtrain = xgb.DMatrix(X, label=y)
    res = {}
    xgb.train({"objective": "multi:softprob", "num_class": 4, "max_depth": 4,
               "eval_metric": "mlogloss"}, dtrain, 10,
              evals=[(dtrain, "train")], evals_result=res, verbose_eval=False)
    ll = res["train-mlogloss"]
    assert ll[-1] < ll[0] * 0.5


# ---------------------------------------------------------------- gblinear

def test_gblinear_regression():
    rng = np.random.RandomState(0)
    X = rng.randn(2000, 8).astype(np.float32)
    true_w = np.array([1.5, -2.0, 0.0, 0.5, 0.0, 3.0, 0.0, -1.0],
                      dtype=np.float32)
    y = X @ true_w + 0.05 * rng.randn(2000).astype(np.float32)
    dtrain = xgb.DMatrix(X, label=y)
    params = {"booster": "gblinear", "objective": "reg:linear", "eta": 0.5,
              "lambda": 0.1, "base_score": 0.0}
    res = {}
    bst = xgb.train(params, dtrain, 60, evals=[(dtrain, "train")],
                    evals_result=res, verbose_eval=False)
    assert res["train-rmse"][-1] < 0.2
    # recovered weights close to truth
    w = np.asarray(bst.gbtree.weight)[:, 0]
    np.testing.assert_allclose(w, true_w, atol=0.15)


def test_gblinear_l1_sparsity():
    rng = np.random.RandomState(1)
    X = rng.randn(1000, 10).astype(np.float32)
    y = (2.0 * X[:, 0]).astype(np.float32)
    dtrain = xgb.DMatrix(X, label=y)
    params = {"booster": "gblinear", "objective": "reg:linear", "eta": 0.5,
              "alpha": 5.0, "lambda": 0.0, "base_score": 0.0}
    bst = xgb.train(params, dtrain, 40, verbose_eval=False)
    w = np.asarray(bst.gbtree.weight)[:, 0]
    # L1 should zero out the 9 irrelevant features
    assert np.sum(np.abs(w[1:]) < 0.05) >= 8
    assert abs(w[0]) > 1.0


def test_gblinear_binary_and_save_load(tmp_path):
    rng = np.random.RandomState(2)
    X = rng.randn(800, 5).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
    dtrain = xgb.DMatrix(X, label=y)
    params = {"booster": "gblinear", "objective": "binary:logistic",
              "eta": 0.8, "lambda": 0.01}
    bst = xgb.train(params, dtrain, 30, verbose_eval=False)
    preds = bst.predict(dtrain)
    assert np.mean((preds > 0.5) == (y == 1)) > 0.9
    path = str(tmp_path / "lin.model")
    bst.save_model(path)
    bst2 = xgb.Booster(model_file=path)
    np.testing.assert_allclose(bst2.predict(dtrain), preds, rtol=1e-6)
    dump = bst2.get_dump()[0]
    assert dump.startswith("bias:")


# ----------------------------------------------------------------- ranking

def make_ranking(n_groups=60, group_size=12, seed=0):
    rng = np.random.RandomState(seed)
    X, y, groups = [], [], []
    for _ in range(n_groups):
        Xi = rng.randn(group_size, 5).astype(np.float32)
        score = Xi[:, 0] * 2 + Xi[:, 1]
        # graded relevance 0..2 by within-group rank
        order = np.argsort(score)
        rel = np.zeros(group_size, dtype=np.float32)
        rel[order[-2:]] = 2.0
        rel[order[-5:-2]] = 1.0
        X.append(Xi)
        y.append(rel)
        groups.append(group_size)
    return np.concatenate(X), np.concatenate(y), np.array(groups)


@pytest.mark.parametrize("objective,metric", [
    ("rank:pairwise", "map"),
    ("rank:ndcg", "ndcg"),
    ("rank:map", "map"),
])
def test_ranking_objectives(objective, metric):
    X, y, groups = make_ranking()
    dtrain = xgb.DMatrix(X, label=y, group=groups)
    params = {"objective": objective, "max_depth": 3, "eta": 0.3,
              "min_child_weight": 0.1}
    res = {}
    xgb.train(params, dtrain, 15, evals=[(dtrain, "train")],
              evals_result=res, verbose_eval=False)
    key = f"train-{metric}"
    assert res[key][-1] > 0.85, res[key]
    assert res[key][-1] > res[key][0]


def test_ranking_metrics_at_n():
    X, y, groups = make_ranking(seed=3)
    dtrain = xgb.DMatrix(X, label=y, group=groups)
    params = {"objective": "rank:pairwise", "max_depth": 3,
              "min_child_weight": 0.1,
              "eval_metric": ["ndcg@5", "map@3", "pre@2"]}
    res = {}
    xgb.train(params, dtrain, 10, evals=[(dtrain, "train")],
              evals_result=res, verbose_eval=False)
    assert res["train-ndcg@5"][-1] > 0.8
    assert res["train-pre@2"][-1] > 0.8


def test_pratio_metric():
    """pratio@r = weighted precision in the top r-fraction by prediction
    (reference EvalPrecisionRatio, evaluation-inl.hpp:302-352)."""
    from xgboost_tpu.metrics import create_metric
    preds = np.array([0.9, 0.8, 0.7, 0.6, 0.5, 0.4, 0.3, 0.2, 0.1, 0.0])
    labels = np.array([1, 1, 0, 1, 0, 0, 0, 0, 0, 1], dtype=np.float64)
    w = np.ones(10)
    m = create_metric("pratio@0.4")
    # top-4: labels 1,1,0,1 -> 3/4
    assert m(preds, labels, w) == pytest.approx(0.75)
    # apratio: mean of running precision 1/1, 2/2, 2/3, 3/4
    m2 = create_metric("apratio@0.4")
    assert m2(preds, labels, w) == pytest.approx((1 + 1 + 2 / 3 + 0.75) / 4)
    # weighted: top-2 with weights 3,1 and labels 1,1 -> 1.0
    m3 = create_metric("pratio@0.2")
    w2 = np.array([3.0, 1.0] + [1.0] * 8)
    assert m3(preds, labels, w2) == pytest.approx(1.0)


def test_ctest_metric():
    """ct-<base> (reference EvalCTest, evaluation-inl.hpp:202-240):
    per-fold held-out evaluation of stacked prediction sets, averaged."""
    from xgboost_tpu.metrics import create_metric
    n = 8
    labels = np.array([0, 1, 0, 1, 0, 1, 0, 1], dtype=np.float64)
    fold = np.array([0, 0, 0, 0, 1, 1, 1, 1])
    # head set (full model) is ignored; set k+1 serves fold k
    head = np.full(n, 0.5)
    set0 = np.where(np.arange(n) % 2, 0.9, 0.1)   # perfect on fold 0
    set1 = np.where(np.arange(n) % 2, 0.1, 0.9)   # inverted on fold 1
    preds = np.concatenate([head, set0, set1])
    m = create_metric("ct-error")
    assert getattr(m, "needs_fold_index", False)
    assert m(preds, labels, np.ones(n), fold_index=fold) == pytest.approx(0.5)
    with pytest.raises(ValueError):
        m(preds, labels, np.ones(n), fold_index=None)
    with pytest.raises(ValueError):
        m(np.concatenate([head, set0]), labels, np.ones(n), fold_index=fold)


def test_device_rank_matches_host_quality():
    """Device LambdaRank (rank_device.py, VERDICT r2 item 4): on-device
    pair sampling + delta weights train to the same metrics as the
    reference-faithful host path (sampling RNGs differ, so the bar is
    trained quality, not gradient equality)."""
    import xgboost_tpu as xgb
    from xgboost_tpu.rank_obj import LambdaRankObj

    rng = np.random.RandomState(7)
    rows, labels, groups = [], [], []
    for g in range(60):
        n = rng.randint(8, 30)
        Xg = rng.rand(n, 6).astype(np.float32)
        score = Xg[:, 0] * 2 + Xg[:, 1] - 0.5 * Xg[:, 2] + 0.3 * rng.randn(n)
        rel = np.zeros(n, np.int32)
        order = np.argsort(-score)
        rel[order[: max(1, n // 6)]] = 2
        rel[order[max(1, n // 6): max(2, n // 3)]] = 1
        rows.append(Xg); labels.append(rel); groups.append(n)
    X = np.concatenate(rows)
    y = np.concatenate(labels).astype(np.float32)

    for kind in ("pairwise", "ndcg", "map"):
        res = {}
        for impl in ("host", "device"):
            d = xgb.DMatrix(X, label=y, group=groups)
            r = {}
            xgb.train({"objective": f"rank:{kind}", "max_depth": 4,
                       "eta": 0.3, "rank_impl": impl,
                       "eval_metric": ["ndcg"]},
                      d, 10, evals=[(d, "train")], evals_result=r,
                      verbose_eval=False)
            res[impl] = r["train-ndcg"][-1]
        assert res["device"] > 0.85, (kind, res)
        assert abs(res["device"] - res["host"]) < 0.05, (kind, res)

    # the device objective is fused-scan eligible (no per-round host
    # work): a no-evals train goes through update_many's fused path
    d = xgb.DMatrix(X, label=y, group=groups)
    bst = xgb.train({"objective": "rank:ndcg", "max_depth": 3, "eta": 0.3},
                    d, 6, verbose_eval=False)
    assert bst.obj.fused_grad(d.info) is not None
    assert not bst.obj.needs_host_margin
    p = bst.predict(d)
    assert p.shape == (len(y),)

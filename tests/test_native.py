"""Native IO runtime tests (native/xgtpu_io.cpp via ctypes): parser
parity with the pure-Python path, rank/npart split loading, page store
round-trip with prefetch."""

import numpy as np
import pytest

from xgboost_tpu import native


pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native IO runtime not built")


def _write_libsvm(path, n=500, f=12, seed=0, sparsity=0.4):
    rng = np.random.RandomState(seed)
    lines = []
    for i in range(n):
        label = rng.randint(0, 2)
        feats = [f"{j}:{rng.rand():.6f}" for j in range(f)
                 if rng.rand() > sparsity]
        lines.append(f"{label} {' '.join(feats)}")
    path.write_text("\n".join(lines) + "\n")
    return str(path)


from xgboost_tpu.data import parse_libsvm_python as _python_parse


def test_native_parser_matches_python(tmp_path):
    path = _write_libsvm(tmp_path / "a.svm")
    got = native.parse_libsvm_native(path)
    want = _python_parse(path)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


def test_native_parser_multithreaded_deterministic(tmp_path):
    path = _write_libsvm(tmp_path / "big.svm", n=20000, f=30, seed=1)
    one = native.parse_libsvm_native(path, nthread=1)
    many = native.parse_libsvm_native(path, nthread=7)
    for a, b in zip(one, many):
        np.testing.assert_array_equal(a, b)


def test_native_parser_rank_split(tmp_path):
    path = _write_libsvm(tmp_path / "s.svm", n=1003)
    shards = [native.parse_libsvm_native(path, rank=r, nparts=3)
              for r in range(3)]
    want = [_python_parse(path, rank=r, nparts=3) for r in range(3)]
    total = 0
    for got, exp in zip(shards, want):
        for g, w in zip(got, exp):
            np.testing.assert_array_equal(g, w)
        total += len(got[3])
    assert total == 1003


def test_native_parser_missing_file():
    with pytest.raises(IOError):
        native.parse_libsvm_native("/nonexistent/x.svm")


def test_page_store_roundtrip(tmp_path):
    rng = np.random.RandomState(2)
    pages = []
    for _ in range(5):
        n = rng.randint(10, 50)
        counts = rng.randint(0, 6, n)
        indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        nnz = int(indptr[-1])
        pages.append((indptr, rng.randint(0, 100, nnz).astype(np.int32),
                      rng.rand(nnz).astype(np.float32)))

    path = str(tmp_path / "pages.bin")
    with native.PageWriter(path) as w:
        for p in pages:
            w.push(*p)

    with native.PageReader(path) as r:
        got = list(r)
        assert len(got) == 5
        for (gi, gx, gv), (wi, wx, wv) in zip(got, pages):
            np.testing.assert_array_equal(gi, wi)
            np.testing.assert_array_equal(gx, wx)
            np.testing.assert_array_equal(gv, wv)
        # reset re-reads from the start
        r.reset()
        again = list(r)
        assert len(again) == 5
        np.testing.assert_array_equal(again[0][0], pages[0][0])


def test_page_store_nonzero_base_indptr(tmp_path):
    """Pages pushed from a slice of a larger CSR (indptr not starting at
    0) must be rebased on disk."""
    indptr = np.array([100, 103, 107], np.int64)
    indices = np.zeros(200, np.int32)
    values = np.zeros(200, np.float32)
    indices[100:107] = np.arange(7)
    values[100:107] = np.arange(7) * 0.5
    path = str(tmp_path / "p.bin")
    with native.PageWriter(path) as w:
        w.push(indptr, indices, values)
    with native.PageReader(path) as r:
        gi, gx, gv = next(r)
        np.testing.assert_array_equal(gi, [0, 3, 7])
        np.testing.assert_array_equal(gx, np.arange(7))
        np.testing.assert_allclose(gv, np.arange(7) * 0.5)


def test_page_reader_bad_file(tmp_path):
    bad = tmp_path / "bad.bin"
    bad.write_bytes(b"not a page file")
    with pytest.raises(IOError):
        native.PageReader(str(bad))


def test_dmatrix_uses_native_parser(tmp_path):
    """DMatrix(path) must produce identical data through the native path."""
    import xgboost_tpu as xgb
    path = _write_libsvm(tmp_path / "d.svm", n=300, f=8, seed=3)
    d = xgb.DMatrix(path)
    want = _python_parse(path)
    np.testing.assert_array_equal(d.indptr, want[0])
    np.testing.assert_array_equal(d.indices, want[1])
    np.testing.assert_array_equal(d.values, want[2])
    np.testing.assert_array_equal(d.get_label(), want[3])


def test_native_parser_malformed_raises(tmp_path):
    """Malformed tokens must raise like the Python fallback, not be
    silently skipped or read across lines."""
    bad1 = tmp_path / "b1.svm"
    bad1.write_text("1 3:\n0 5:1.0\n")  # empty value at end of line
    with pytest.raises(ValueError):
        native.parse_libsvm_native(str(bad1))
    bad2 = tmp_path / "b2.svm"
    bad2.write_text("1 7 2:1.0\n")  # token without colon
    with pytest.raises(ValueError):
        native.parse_libsvm_native(str(bad2))


def test_native_parser_missing_raises_filenotfound():
    with pytest.raises(FileNotFoundError):
        native.parse_libsvm_native("/nonexistent/x.svm")


def test_page_writer_empty_indptr_raises(tmp_path):
    w = native.PageWriter(str(tmp_path / "e.bin"))
    with pytest.raises(ValueError):
        w.push(np.array([], np.int64), np.array([], np.int32),
               np.array([], np.float32))
    w.close()

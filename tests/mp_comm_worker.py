"""Worker for test_obs.py: per-rank collective stats across processes.

Trains a few dsplit=row rounds over the global (cross-process) mesh and
writes one JSON file per rank with:

- ``totals``        — this rank's cumulative comm stats (obs/comm.py)
- ``mock_calls``    — the mock seam's collective-call count (the number
  ``xgbtpu_comm_allreduce_total`` must match)
- ``per_round``     — per-round (count, bytes, seconds) tallies
- ``aggregated``    — totals summed ACROSS workers via the existing
  mesh collective (ShardedDMatrix.allsum)
- ``metrics_text``  — the rank's rendered /metrics registry body

Usage: mp_comm_worker.py <libsvm_path> <out_prefix> <n_rounds>
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from xgboost_tpu.parallel.launch import init_worker  # noqa: E402

assert init_worker(local_device_count=2)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    path, out_prefix, n_rounds = sys.argv[1], sys.argv[2], int(sys.argv[3])
    rank = jax.process_index()

    import xgboost_tpu as xgb
    from xgboost_tpu.obs import comm, registry
    from xgboost_tpu.parallel import mock

    params = {"objective": "binary:logistic", "max_depth": 3,
              "eta": 0.7, "max_bin": 32, "dsplit": "row"}
    dtrain = xgb.DMatrix(path)
    # per-round updates (evals force the non-fused path, so each round
    # has its own begin_round + collective launch)
    xgb.train(params, dtrain, n_rounds, evals=[(dtrain, "train")],
              verbose_eval=False)

    out = {
        "rank": rank,
        "totals": comm.totals(),
        "mock_calls": mock.collective_calls(),
        "per_round": {str(k): v for k, v in comm.all_round_stats().items()},
        "aggregated": comm.aggregate_across_workers(),
        "metrics_text": registry().render(),
    }
    with open(f"{out_prefix}.rank{rank}.json", "w") as f:
        json.dump(out, f)
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("done")


if __name__ == "__main__":
    main()

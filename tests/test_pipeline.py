"""Continuous-training pipeline (xgboost_tpu.pipeline, PIPELINE.md).

Acceptance criteria covered here:
(a) end-to-end train→gate→publish→registry-reload: a published cycle
    hot-reloads a live ModelRegistry to exactly the gated hash;
(b) a torn publish (fault-injected; a real SIGKILL mid-publish is
    strictly weaker — atomic_write leaves old-or-new) and a corrupted
    candidate BOTH leave the poller serving the incumbent
    bit-identically, and the next clean cycle publishes;
(c) a kill mid-train resumes from the checkpoint ring and finishes
    BIT-identical to an uninterrupted cycle;
(d) a kill between gate and publish re-gates on restart and then
    publishes;
(e) warm-start continuation (``train(init_model=)``) appends rounds
    bit-identical to one uninterrupted run, and a model reload under a
    cached DMatrix never mixes tree windows.

Faults are injected through reliability/faults.py inside the REAL
write/read paths; the subprocess SIGKILL variant lives in
``tools/chaos_loop.py --pipeline`` (PIPELINE_CHAOS.json).
"""

import hashlib
import os

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.obs.metrics import pipeline_metrics
from xgboost_tpu.pipeline import (CallableDataSource, ContinuousTrainer,
                                  EvalGate, FileDataSource, Publisher,
                                  SyntheticDataSource)
from xgboost_tpu.reliability import faults
from xgboost_tpu.serving import ModelRegistry

PARAMS = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.4,
          "silent": 1}


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    faults.clear_faults()
    yield
    faults.clear_faults()


def make_trainer(workdir, publish, rounds=2, source=None, gate=None,
                 params=None):
    source = source if source is not None else SyntheticDataSource(
        n_rows=300, n_features=6, seed=0)
    gate = gate if gate is not None else EvalGate(max_regression=0.5)
    return ContinuousTrainer(str(publish), source, str(workdir),
                             rounds_per_cycle=rounds,
                             params=dict(params or PARAMS), gate=gate,
                             quiet=True)


def make_registry(publish):
    return ModelRegistry(str(publish), poll_sec=0, warmup=False,
                         min_bucket=8, max_bucket=16)


def file_hash(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def gated_hashes(trainer):
    try:
        with open(trainer.gated_log) as f:
            # tolerate a torn final line (the ledger's crash contract)
            return [parts[1] for parts in
                    (line.split() for line in f) if len(parts) >= 2]
    except OSError:
        return []


def states_equal(a, b):
    sa, sb = a.gbtree.get_state(), b.gbtree.get_state()
    assert set(sa) == set(sb)
    return all(np.array_equal(sa[k], sb[k]) for k in sa)


# ------------------------------------------------------------ happy path
def test_cycles_append_and_registry_reloads_gated_hash(tmp_path):
    publish = tmp_path / "published.model"
    tr = make_trainer(tmp_path / "wd", publish, rounds=2)
    out0 = tr.run_cycle()
    assert out0["status"] == "published"
    assert out0["gate"]["reason"] == "no incumbent (cold start)"
    reg = make_registry(publish)
    assert reg.content_hash == gated_hashes(tr)[-1]

    out1 = tr.run_cycle()
    assert out1["status"] == "published"
    # warm start appended: 2 cycles x 2 rounds
    bst = xgb.Booster(model_file=str(publish))
    assert bst.gbtree.num_trees == 4
    # the live registry hot-reloads to EXACTLY the newly gated hash
    assert reg.check_reload() is True
    assert reg.content_hash == gated_hashes(tr)[-1] == file_hash(publish)
    # the ledger was written BEFORE the publish path ever changed
    assert len(gated_hashes(tr)) == 2
    reg.stop()


def test_gate_fail_quarantines_and_keeps_incumbent(tmp_path):
    publish = tmp_path / "published.model"
    tr = make_trainer(tmp_path / "wd", publish, rounds=2)
    assert tr.run_cycle()["status"] == "published"
    before = publish.read_bytes()
    pm = pipeline_metrics()
    fails0, q0 = pm.gate_fail.value, pm.quarantines.value
    tr.gate = EvalGate(min_delta=10.0)  # unmeetable
    out = tr.run_cycle()
    assert out["status"] == "gate_failed"
    assert publish.read_bytes() == before  # incumbent untouched
    assert os.listdir(tr.quarantine_dir)  # candidate preserved aside
    assert not os.path.exists(tr.candidate_path)
    assert pm.gate_fail.value == fails0 + 1
    assert pm.quarantines.value == q0 + 1
    assert len(gated_hashes(tr)) == 1  # rejected hash never ledgered
    # the pipeline keeps training: the NEXT cycle can publish again
    tr.gate = EvalGate(max_regression=0.5)
    assert tr.run_cycle()["status"] == "published"


# -------------------------------------------------- corruption / torn I/O
def test_corrupt_candidate_never_published(tmp_path):
    publish = tmp_path / "published.model"
    tr = make_trainer(tmp_path / "wd", publish, rounds=2)
    assert tr.run_cycle()["status"] == "published"
    reg = make_registry(publish)
    before = publish.read_bytes()
    # flip a bit in the candidate as it is written: CRC catches it at
    # the gate, BEFORE any publish byte moves
    faults.inject("bit_flip", 256, path_sub="candidate.model")
    out = tr.run_cycle()
    assert out["status"] == "gate_failed"
    assert "failed verification" in out["gate"]["reason"]
    assert publish.read_bytes() == before
    assert reg.check_reload() is False  # poller saw nothing
    assert reg.reload_failures == 0
    # next clean cycle publishes
    assert tr.run_cycle()["status"] == "published"
    assert reg.check_reload() is True
    assert reg.content_hash == gated_hashes(tr)[-1]
    reg.stop()


def test_torn_publish_invisible_to_poller_then_heals(tmp_path):
    """The acceptance e2e: a publish whose bytes are torn on disk
    (strictly worse than a SIGKILL mid-publish, which atomic_write
    reduces to old-or-new) leaves the registry serving the incumbent
    bit-identically; the next clean cycle heals the publish path from
    the incumbent ring replica and publishes."""
    publish = tmp_path / "published.model"
    tr = make_trainer(tmp_path / "wd", publish, rounds=2)
    assert tr.run_cycle()["status"] == "published"
    reg = make_registry(publish)
    incumbent_hash = reg.content_hash
    X = np.random.RandomState(0).rand(16, 6).astype(np.float32)
    incumbent_pred = np.asarray(reg.engine.predict(X))

    faults.inject("torn_write", 200, path_sub="published.model")
    out = tr.run_cycle()  # publishes torn bytes (simulated media fault)
    assert out["status"] == "published"  # the pipeline cannot know yet
    # the poller CRC-rejects the torn file and keeps the incumbent,
    # serving BIT-identical predictions
    assert reg.check_reload() is False
    assert reg.reload_failures == 1
    assert reg.content_hash == incumbent_hash
    assert np.array_equal(np.asarray(reg.engine.predict(X)),
                          incumbent_pred)

    # next clean cycle: the trainer heals the corrupt publish path from
    # its verified backup, then trains + publishes a new gated model
    out2 = tr.run_cycle()
    assert out2["status"] == "published"
    assert reg.check_reload() is True
    assert reg.content_hash == gated_hashes(tr)[-1]
    # every hash the poller ever built is in the gated ledger
    assert reg.content_hash in gated_hashes(tr)
    assert incumbent_hash in gated_hashes(tr)
    reg.stop()


# ------------------------------------------------------- crash recovery
def test_mid_train_kill_resumes_bit_identical(tmp_path):
    """A cycle killed mid-train resumes from the checkpoint ring and
    finishes BIT-identical to an uninterrupted cycle (deterministic
    data source + continued iteration numbering)."""
    src = lambda: SyntheticDataSource(n_rows=300, n_features=6, seed=0)
    # reference: two uninterrupted cycles (default fused segment size)
    ref_pub = tmp_path / "ref.model"
    ref = make_trainer(tmp_path / "ref_wd", ref_pub, rounds=4,
                       source=src())
    assert ref.run_cycle()["status"] == "published"
    assert ref.run_cycle()["status"] == "published"

    # the interrupted trainer fuses 2 rounds per dispatch so the ring
    # gets a mid-cycle boundary write (ckpt-000002) before the kill
    seg_params = dict(PARAMS, rounds_per_dispatch=2)
    pub = tmp_path / "published.model"
    tr = make_trainer(tmp_path / "wd", pub, rounds=4, source=src(),
                      params=seg_params)
    assert tr.run_cycle()["status"] == "published"
    # "kill" cycle 1 mid-train: the second segment's boundary write
    # dies, leaving 2 rounds in the ring
    faults.inject("enospc", path_sub="ckpt-000004")
    summary = tr.run(cycles=1)
    assert summary["errors"] == 1
    assert tr._read_state() == {"cycle": 1, "phase": "train"}

    # a fresh process (new trainer instance, same workdir) resumes
    pm = pipeline_metrics()
    r0 = pm.resumes.value
    tr2 = make_trainer(tmp_path / "wd", pub, rounds=4, source=src())
    assert tr2.run_cycle()["status"] == "published"
    assert pm.resumes.value == r0 + 1
    assert states_equal(xgb.Booster(model_file=str(pub)),
                        xgb.Booster(model_file=str(ref_pub)))


def test_regate_on_restart_after_publish_failure(tmp_path):
    """Killed between gate and publish: the restart re-gates the
    candidate from its bytes and then publishes."""
    publish = tmp_path / "published.model"
    tr = make_trainer(tmp_path / "wd", publish, rounds=2)
    assert tr.run_cycle()["status"] == "published"
    before = publish.read_bytes()
    faults.inject("enospc", path_sub="published.model")
    summary = tr.run(cycles=1)
    assert summary["errors"] == 1
    assert publish.read_bytes() == before  # atomic: old file intact
    assert tr._read_state() == {"cycle": 1, "phase": "publish"}
    assert os.path.exists(tr.candidate_path)

    pm = pipeline_metrics()
    r0 = pm.resumes.value
    tr2 = make_trainer(tmp_path / "wd", publish, rounds=2)
    out = tr2.run_cycle()
    assert out["status"] == "published" and out["cycle"] == 1
    assert pm.resumes.value == r0 + 1
    assert file_hash(publish) == out["gate"]["model_hash"]
    assert tr2._read_state() == {"cycle": 2, "phase": "train"}


def test_crash_after_publish_before_advance_finalizes(tmp_path):
    """Killed BETWEEN a completed publish and the cursor advance: the
    candidate is now the incumbent, and a strict-improvement gate
    (min_delta > 0) re-gating it against itself would quarantine the
    live model — the restart must recognize the completed publish and
    finalize instead."""
    publish = tmp_path / "published.model"
    tr = make_trainer(tmp_path / "wd", publish, rounds=2,
                      gate=EvalGate(min_delta=0.001))
    assert tr.run_cycle()["status"] == "published"  # cold start
    # recreate the crash window: published bytes back as the candidate,
    # cursor still at phase "publish"
    with open(tr.candidate_path, "wb") as f:
        f.write(publish.read_bytes())
    tr._write_state({"cycle": 0, "phase": "publish"})
    backup_before = open(tr.backup_path, "rb").read()

    pm = pipeline_metrics()
    q0, gf0, r0 = (pm.quarantines.value, pm.gate_fail.value,
                   pm.resumes.value)
    tr2 = make_trainer(tmp_path / "wd", publish, rounds=2,
                       gate=EvalGate(min_delta=0.001))
    out = tr2.run_cycle()
    assert out["status"] == "published" and out["resumed"] is True
    assert pm.resumes.value == r0 + 1
    # the live model was NOT quarantined or gate-failed
    assert pm.quarantines.value == q0
    assert pm.gate_fail.value == gf0
    assert not os.path.exists(tr2.quarantine_dir)
    # epilogue completed: backup refreshed, cursor advanced
    assert open(tr2.backup_path, "rb").read() == backup_before
    assert tr2._read_state() == {"cycle": 1, "phase": "train"}


def test_idle_when_no_fresh_data(tmp_path):
    publish = tmp_path / "published.model"
    src = CallableDataSource(lambda cycle: None)
    tr = make_trainer(tmp_path / "wd", publish, source=src)
    assert tr.run_cycle()["status"] == "idle"
    assert not os.path.exists(publish)


def test_regate_survives_rotated_train_file(tmp_path):
    """The re-gate needs ONLY the holdout: a producer that rotated the
    cycle's fresh train file away between the kill and the restart
    must not wedge the recovery."""
    def write_svm(path, seed, n=150):
        rng = np.random.RandomState(seed)
        X = rng.rand(n, 4)
        y = (X[:, 0] > 0.5).astype(int)
        with open(path, "w") as f:
            for i in range(n):
                f.write(f"{y[i]} " + " ".join(
                    f"{j}:{X[i, j]:.6f}" for j in range(4)) + "\n")
    write_svm(tmp_path / "fresh-0.libsvm", 1)
    write_svm(tmp_path / "holdout.libsvm", 9)
    publish = tmp_path / "published.model"

    def src():
        return FileDataSource(str(tmp_path / "fresh-{cycle}.libsvm"),
                              str(tmp_path / "holdout.libsvm"))
    tr = make_trainer(tmp_path / "wd", publish, rounds=2, source=src())
    assert tr.run_cycle()["status"] == "published"
    write_svm(tmp_path / "fresh-1.libsvm", 2)
    faults.inject("enospc", path_sub="published.model")
    assert tr.run(cycles=1)["errors"] == 1  # died at publish
    faults.clear_faults()
    os.remove(tmp_path / "fresh-1.libsvm")  # producer rotated it away

    tr2 = make_trainer(tmp_path / "wd", publish, rounds=2, source=src())
    out = tr2.run_cycle()
    assert out["status"] == "published" and out["cycle"] == 1


# --------------------------------------------------------- file source
def test_file_datasource_cycle_substitution(tmp_path):
    def write_svm(path, seed, n=120):
        rng = np.random.RandomState(seed)
        X = rng.rand(n, 4)
        y = (X[:, 0] > 0.5).astype(int)
        with open(path, "w") as f:
            for i in range(n):
                f.write(f"{y[i]} " + " ".join(
                    f"{j}:{X[i, j]:.6f}" for j in range(4)) + "\n")
    write_svm(tmp_path / "fresh-0.libsvm", 1)
    write_svm(tmp_path / "holdout.libsvm", 9)
    src = FileDataSource(str(tmp_path / "fresh-{cycle}.libsvm"),
                         str(tmp_path / "holdout.libsvm"))
    d0 = src.next_cycle(0)
    assert d0 is not None and d0[0].num_row == 120
    # holdout is cached across cycles (same object)
    write_svm(tmp_path / "fresh-1.libsvm", 2)
    assert src.next_cycle(1)[1] is d0[1]
    assert src.next_cycle(2) is None  # no fresh-2 yet: idle


def test_file_datasource_holdout_refreshes_on_rewrite(tmp_path):
    """A producer rewriting the holdout file IN PLACE (same path) must
    invalidate the cache — the (mtime, size) stamp, not the path, pins
    staleness.  An unchanged file keeps serving the cached object."""
    def write_svm(path, seed, n=120):
        rng = np.random.RandomState(seed)
        X = rng.rand(n, 4)
        y = (X[:, 0] > 0.5).astype(int)
        with open(path, "w") as f:
            for i in range(n):
                f.write(f"{y[i]} " + " ".join(
                    f"{j}:{X[i, j]:.6f}" for j in range(4)) + "\n")
    write_svm(tmp_path / "holdout.libsvm", 9)
    src = FileDataSource(str(tmp_path / "fresh-{cycle}.libsvm"),
                         str(tmp_path / "holdout.libsvm"))
    h0 = src.holdout_for(0)
    assert src.holdout_for(1) is h0  # untouched file: cached object
    # in-place rewrite with fresh bytes (row count changes too, so the
    # stamp moves even on coarse-mtime filesystems)
    write_svm(tmp_path / "holdout.libsvm", 10, n=140)
    h1 = src.holdout_for(2)
    assert h1 is not h0 and h1.num_row == 140
    assert src.holdout_for(3) is h1


# ----------------------------------------------------------- warm start
def test_train_init_model_continuation_bit_identical(tmp_path):
    """train(init_model=) appends rounds whose iteration numbering
    (fold_in seeding, subsample draws) continues the loaded ensemble's
    — the continued model is bit-identical to one uninterrupted run."""
    rng = np.random.RandomState(3)
    X = rng.rand(400, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    p = {**PARAMS, "subsample": 0.7, "seed": 5}
    full = xgb.train(p, d, 6)

    half = xgb.train(p, xgb.DMatrix(X, label=y), 3)
    path = str(tmp_path / "half.model")
    half.save_model(path)
    cont = xgb.train(p, xgb.DMatrix(X, label=y), 3, init_model=path)
    assert cont.gbtree.num_trees == 6
    assert states_equal(full, cont)
    assert np.array_equal(full.predict(d), cont.predict(d))


def test_train_rejects_both_aliases(tmp_path):
    X = np.random.RandomState(0).rand(50, 4).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train(PARAMS, d, 1)
    with pytest.raises(ValueError, match="not both"):
        xgb.train(PARAMS, d, 1, xgb_model=bst, init_model=bst)


def test_reload_under_cached_dmatrix_never_mixes_windows(tmp_path):
    """predict_incremental state after a model reload: the cached
    margin must rebuild for the NEW ensemble, not mix tree windows."""
    rng = np.random.RandomState(1)
    X = rng.rand(200, 5).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train(PARAMS, d, 3, evals=[(d, "train")],
                    verbose_eval=False)
    small = str(tmp_path / "small.model")
    bst.save_model(small)
    # keep training the same booster on the same cached DMatrix
    bst2 = xgb.train(PARAMS, d, 3, xgb_model=bst, evals=[(d, "train")],
                     verbose_eval=False)
    assert bst2.gbtree.num_trees == 6
    # hot-reload the SMALLER model into the live booster: predictions
    # on the still-cached DMatrix must equal a fresh load's
    bst2.load_model(small)
    fresh = xgb.Booster(model_file=small)
    assert np.array_equal(bst2.predict(d), fresh.predict(d))
    # belt: an ensemble swapped in directly (no cache clear) must also
    # never serve a margin folding more trees than exist
    bst3 = xgb.train(PARAMS, d, 3, evals=[(d, "train")],
                     verbose_eval=False)
    entry = bst3._entry(d)
    assert entry.applied == bst3.gbtree.num_trees
    bst3.gbtree = fresh.gbtree  # smaller ensemble, cache NOT cleared
    bst3._sync_margin(entry)
    assert entry.applied == fresh.gbtree.num_trees
    assert np.array_equal(bst3.predict(d), fresh.predict(d))


# ------------------------------------------------------------ CLI + obs
def test_cli_usage_lists_pipeline_params(capsys):
    from xgboost_tpu.cli import main as cli_main
    from xgboost_tpu.config import PIPELINE_PARAMS
    assert cli_main([]) == 0
    out = capsys.readouterr().out
    assert "pipeline" in out
    for name in PIPELINE_PARAMS:
        assert name in out, f"{name} missing from CLI usage"


def test_pipeline_metrics_families_render():
    pm = pipeline_metrics()
    text = pm.render()
    for fam in ("xgbtpu_pipeline_cycles_total",
                "xgbtpu_pipeline_cycle_seconds",
                "xgbtpu_pipeline_gate_pass_total",
                "xgbtpu_pipeline_gate_fail_total",
                "xgbtpu_pipeline_publishes_total",
                "xgbtpu_pipeline_publish_failures_total",
                "xgbtpu_pipeline_publish_seconds_total",
                "xgbtpu_pipeline_trees_published_total",
                "xgbtpu_pipeline_quarantines_total",
                "xgbtpu_pipeline_resumes_total",
                "xgbtpu_pipeline_incumbent_age_seconds"):
        assert f"# TYPE {fam} " in text, fam
    # the group rides the process-wide registry (one scrape covers it)
    from xgboost_tpu.obs import registry
    assert "xgbtpu_pipeline_cycles_total" in registry().render()

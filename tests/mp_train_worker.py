"""Worker for test_launch.py: FULL Booster training across processes.

Each process holds the replicated host copy of the data; compute shards
over the global (cross-process) mesh.  Writes the final model + eval
line per rank so the test can assert cross-rank identity and quality.
Usage: mp_train_worker.py <libsvm_path> <out_prefix>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from xgboost_tpu.parallel.launch import init_worker  # noqa: E402

assert init_worker(local_device_count=2)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    path, out_prefix = sys.argv[1], sys.argv[2]
    rank = jax.process_index()
    assert jax.device_count() == 4

    import xgboost_tpu as xgb

    params = {"objective": "binary:logistic", "max_depth": 3,
              "eta": 0.7, "max_bin": 32, "dsplit": "row"}
    dtrain = xgb.DMatrix(path)
    res = {}
    bst = xgb.train(params, dtrain, 5, evals=[(dtrain, "train")],
                    evals_result=res, verbose_eval=False)
    err = float(res["train-error"][-1])
    bst.save_model(f"{out_prefix}.rank{rank}.model")
    with open(f"{out_prefix}.rank{rank}.err", "w") as f:
        f.write(f"{err:.6f}\n")

    # no-evals training takes the FUSED multi-round scan across the
    # global (cross-process) mesh; must bit-match the per-round model
    bst_f = xgb.train(params, xgb.DMatrix(path), 5, verbose_eval=False)
    bst_f.save_model(f"{out_prefix}.rank{rank}.fused.model")
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("done")


if __name__ == "__main__":
    main()

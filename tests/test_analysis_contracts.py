"""xgtpu-lint v2: whole-repo contract rules XGT008-XGT012
(ANALYSIS.md §v2, analysis/contracts.py).

Layers:

1. **fixture mini-trees** — each rule fires on a known-bad tree
   (unknown endpoint, method mismatch, undocumented/stale metric
   family, label drift, undocumented/stale knob, unused param key,
   lock-order cycle) and stays quiet on the good twin;
2. **inventory** — ANALYSIS_CONTRACTS.json roundtrip, drift detection,
   and freshness + non-emptiness of the committed file;
3. **negative tests on REAL facts** — deleting a metric row from a
   copy of OBSERVABILITY.md / a knob row from a copy of README.md
   produces a finding against the real package's extraction;
4. **enforcement** — the tier-1 gate: the whole repo is contract-clean;
5. **runtime cross-check** — a seeded FeatureStore + Membership stress
   run under the LockRaceChecker, asserting every lock order the
   runtime checker observes is an edge of the static XGT011 graph;
6. **route sweep** — every extracted handler route answers without the
   no-route 404 body, and unknown paths get the one consistent
   ``{"error": "no route <path>"}`` JSON 404 on all three servers.

The mini-tree and inventory layers are pure stdlib-AST work; the
stress and route layers run tiny CPU jax programs — no mesh/AxisType
gating needed.
"""

import json
import os
import random
import shutil
import threading

import pytest

from xgboost_tpu.analysis.__main__ import main as lint_main
from xgboost_tpu.analysis.contracts import (CONTRACT_CODES,
                                            ContractEngine,
                                            default_engine)

PKG_DIR = os.path.dirname(os.path.abspath(__import__(
    "xgboost_tpu").__file__))
REPO_ROOT = os.path.dirname(PKG_DIR)
TOOLS_DIR = os.path.join(REPO_ROOT, "tools")


def engine_for(tmp_path, codes=None, fact_paths=None) -> ContractEngine:
    return ContractEngine(str(tmp_path), fact_paths=fact_paths,
                          codes=codes)


def run_codes(tmp_path, codes=None):
    act, sup = engine_for(tmp_path, codes=codes).run()
    return act, sup


def messages(findings):
    return "\n".join(f.render() for f in findings)


# ------------------------------------------------------------------ XGT008
SERVER_SRC = """\
from http.server import BaseHTTPRequestHandler
class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):
        if self.path == "/healthz":
            return
        if self.path in ("/metrics", "/status"):
            return
    def do_POST(self):
        if self.path == "/predict":
            return
"""


class TestHTTPContractParity:
    def test_unknown_endpoint_fires(self, tmp_path):
        (tmp_path / "server.py").write_text(SERVER_SRC)
        (tmp_path / "client.py").write_text(
            'import http.client\n'
            'def go(conn):\n'
            '    conn.request("POST", "/predicz", body=b"")\n')
        act, _ = run_codes(tmp_path, {"XGT008"})
        assert [f.rule for f in act] == ["XGT008"]
        assert "/predicz" in act[0].message
        assert act[0].path.endswith("client.py")

    def test_method_mismatch_fires(self, tmp_path):
        (tmp_path / "server.py").write_text(SERVER_SRC)
        (tmp_path / "client.py").write_text(
            'def go(conn):\n'
            '    conn.request("GET", "/predict")\n')
        act, _ = run_codes(tmp_path, {"XGT008"})
        assert len(act) == 1 and "method mismatch" in act[0].message

    def test_matching_pair_is_clean(self, tmp_path):
        (tmp_path / "server.py").write_text(SERVER_SRC)
        (tmp_path / "client.py").write_text(
            'import urllib.request\n'
            'def go(conn, url, post):\n'
            '    conn.request("POST", "/predict", body=b"")\n'
            '    urllib.request.urlopen(url + "/healthz")\n'
            '    post("/predict")  # _post-style helpers are POST\n'
            'def _post(path):\n'
            '    pass\n'
            'def fwd(rep, call):\n'
            '    call(rep, "GET", "/status", b"", {})\n')
        act, _ = run_codes(tmp_path, {"XGT008"})
        assert not act, messages(act)

    def test_inline_suppression_silences(self, tmp_path):
        (tmp_path / "server.py").write_text(SERVER_SRC)
        (tmp_path / "client.py").write_text(
            'def go(conn):\n'
            '    conn.request("POST", "/elsewhere")'
            '  # xgtpu: disable=XGT008\n')
        act, sup = run_codes(tmp_path, {"XGT008"})
        assert not act and len(sup) == 1

    def test_no_handlers_means_no_client_findings(self, tmp_path):
        # a tree with clients but no route tables (another service's
        # client library) has nothing to hold the calls against
        (tmp_path / "client.py").write_text(
            'def go(conn):\n'
            '    conn.request("POST", "/whatever")\n')
        act, _ = run_codes(tmp_path, {"XGT008"})
        assert not act


# ------------------------------------------------------------------ XGT009
DOC_HEADER = "| family | type |\n|---|---|\n"


class TestMetricFamilyDrift:
    def test_undocumented_family_fires(self, tmp_path):
        (tmp_path / "m.py").write_text(
            'c = Counter("xgbtpu_foo_total", "h")\n')
        (tmp_path / "OBSERVABILITY.md").write_text(DOC_HEADER)
        act, _ = run_codes(tmp_path, {"XGT009"})
        assert len(act) == 1 and "xgbtpu_foo_total" in act[0].message
        assert act[0].path.endswith("m.py")

    def test_stale_doc_row_fires_at_doc_line(self, tmp_path):
        (tmp_path / "m.py").write_text(
            'c = Counter("xgbtpu_foo_total", "h")\n')
        (tmp_path / "OBSERVABILITY.md").write_text(
            DOC_HEADER + "| `xgbtpu_foo_total` | counter |\n"
                         "| `xgbtpu_gone_total` | counter |\n")
        act, _ = run_codes(tmp_path, {"XGT009"})
        assert len(act) == 1 and "xgbtpu_gone_total" in act[0].message
        assert act[0].path.endswith("OBSERVABILITY.md")
        assert act[0].line == 4

    def test_brace_expansion_and_fstring_resolution(self, tmp_path):
        (tmp_path / "m.py").write_text(
            'OPS = ("hits", "misses")\n'
            'class G:\n'
            '    def __init__(self, prefix="xgbtpu_store"):\n'
            '        p = prefix\n'
            '        for op in OPS:\n'
            '            Counter(f"{p}_{op}_total", "h")\n')
        (tmp_path / "OBSERVABILITY.md").write_text(
            DOC_HEADER + "| `xgbtpu_store_{hits,misses}_total` | c |\n")
        act, _ = run_codes(tmp_path, {"XGT009"})
        assert not act, messages(act)

    def test_label_drift_vs_doc_fires(self, tmp_path):
        (tmp_path / "m.py").write_text(
            'c = LabeledCounter("xgbtpu_l_total", "site", "h")\n')
        (tmp_path / "OBSERVABILITY.md").write_text(
            DOC_HEADER + "| `xgbtpu_l_total{kind=}` | counter |\n")
        act, _ = run_codes(tmp_path, {"XGT009"})
        assert len(act) == 1 and "label drift" in act[0].message

    def test_inconsistent_labels_across_sites_fire(self, tmp_path):
        (tmp_path / "a.py").write_text(
            'c = LabeledCounter("xgbtpu_l_total", "site", "h")\n')
        (tmp_path / "b.py").write_text(
            'c = LabeledCounter("xgbtpu_l_total", "kind", "h")\n')
        act, _ = run_codes(tmp_path, {"XGT009"})
        assert any("INCONSISTENT" in f.message for f in act)

    def test_mixed_labeled_unlabeled_family_does_not_crash_inventory(
            self, tmp_path):
        # review r8: sorting family tuples compared a None label
        # against a str — the inventory must survive the exact input
        # the INCONSISTENT-labels finding exists to report
        (tmp_path / "m.py").write_text(
            'c = Counter("xgbtpu_x_total", "h")\n'
            'd = LabeledCounter("xgbtpu_x_total", "op", "h")\n')
        eng = engine_for(tmp_path, codes={"XGT009"})
        inv = eng.inventory()
        assert "xgbtpu_x_total" in inv["metric_families"]
        act, _ = eng.run()
        assert any("INCONSISTENT" in f.message for f in act)

    def test_no_doc_file_skips_doc_checks(self, tmp_path):
        (tmp_path / "m.py").write_text(
            'c = Counter("xgbtpu_foo_total", "h")\n')
        act, _ = run_codes(tmp_path, {"XGT009"})
        assert not act

    def test_removing_real_doc_row_is_detected(self, tmp_path):
        """THE acceptance negative: drop one family from a copy of the
        real OBSERVABILITY.md and lint the real package against it."""
        text = open(os.path.join(REPO_ROOT, "OBSERVABILITY.md")).read()
        doctored = "\n".join(
            line for line in text.splitlines()
            if "xgbtpu_fleet_shed_total" not in line)
        assert doctored != text
        (tmp_path / "OBSERVABILITY.md").write_text(doctored)
        eng = engine_for(tmp_path, codes={"XGT009"},
                         fact_paths=[PKG_DIR])
        act, _ = eng.run()
        assert any("xgbtpu_fleet_shed_total" in f.message
                   and f.rule == "XGT009" for f in act), messages(act)


# ------------------------------------------------------------------ XGT010
class TestKnobDrift:
    def test_undocumented_knob_fires(self, tmp_path):
        (tmp_path / "k.py").write_text(
            'import os\nv = os.environ.get("XGBTPU_FIX_KNOB")\n')
        (tmp_path / "README.md").write_text("nothing here\n")
        act, _ = run_codes(tmp_path, {"XGT010"})
        assert len(act) == 1 and "XGBTPU_FIX_KNOB" in act[0].message
        assert act[0].path.endswith("k.py")

    def test_documented_knob_is_clean_and_module_const_resolves(
            self, tmp_path):
        (tmp_path / "k.py").write_text(
            'import os\n'
            'KNOB = "XGBTPU_FIX_KNOB"\n'
            'v = os.environ.get(KNOB)\n')
        (tmp_path / "README.md").write_text(
            "| `XGBTPU_FIX_KNOB` | `0` | a knob |\n")
        act, _ = run_codes(tmp_path, {"XGT010"})
        assert not act, messages(act)

    def test_stale_readme_knob_fires_at_doc_line(self, tmp_path):
        (tmp_path / "k.py").write_text(
            'import os\nv = os.environ.get("XGBTPU_FIX_KNOB")\n')
        (tmp_path / "README.md").write_text(
            "| `XGBTPU_FIX_KNOB` | live |\n"
            "| `XGBTPU_GONE_KNOB` | stale |\n")
        act, _ = run_codes(tmp_path, {"XGT010"})
        assert len(act) == 1 and "XGBTPU_GONE_KNOB" in act[0].message
        assert act[0].path.endswith("README.md") and act[0].line == 2

    def test_unused_param_table_key_fires(self, tmp_path):
        (tmp_path / "config.py").write_text(
            'SERVE_PARAMS = {"serve_x": (1, "help")}\n')
        act, _ = run_codes(tmp_path, {"XGT010"})
        assert len(act) == 1 and "'serve_x'" in act[0].message
        (tmp_path / "cli.py").write_text('v = sp["serve_x"]\n')
        act, _ = run_codes(tmp_path, {"XGT010"})
        assert not act

    def test_removing_real_readme_knob_row_is_detected(self, tmp_path):
        """Acceptance negative #2: drop a knob row from a copy of the
        real README and lint the real package against it."""
        text = open(os.path.join(REPO_ROOT, "README.md")).read()
        doctored = "\n".join(
            line for line in text.splitlines()
            if "XGBTPU_HIST_RTILE" not in line)
        assert doctored != text
        (tmp_path / "README.md").write_text(doctored)
        eng = engine_for(tmp_path, codes={"XGT010"},
                         fact_paths=[PKG_DIR])
        act, _ = eng.run()
        assert any("XGBTPU_HIST_RTILE" in f.message
                   and f.rule == "XGT010" for f in act), messages(act)


# ------------------------------------------------------------------ XGT012
class TestHTTPTimeoutDiscipline:
    def test_timeoutless_urlopen_fires(self, tmp_path):
        (tmp_path / "c.py").write_text(
            'import urllib.request\n'
            'def go(url):\n'
            '    return urllib.request.urlopen(url)\n')
        act, _ = run_codes(tmp_path, {"XGT012"})
        assert len(act) == 1 and "urlopen" in act[0].message
        assert "timeout" in act[0].message

    def test_timeoutless_connection_fires(self, tmp_path):
        (tmp_path / "c.py").write_text(
            'import http.client\n'
            'def go(host, port):\n'
            '    return http.client.HTTPConnection(host, port)\n')
        act, _ = run_codes(tmp_path, {"XGT012"})
        assert len(act) == 1 and "HTTPConnection" in act[0].message

    def test_explicit_timeouts_are_clean(self, tmp_path):
        (tmp_path / "c.py").write_text(
            'import http.client, urllib.request\n'
            'def go(host, port, url, req):\n'
            '    http.client.HTTPConnection(host, port, timeout=3.0)\n'
            '    http.client.HTTPConnection(host, port, 5.0)\n'
            '    urllib.request.urlopen(url, timeout=2.0)\n'
            '    urllib.request.urlopen(req, None, 2.0)\n')
        act, _ = run_codes(tmp_path, {"XGT012"})
        assert not act, messages(act)

    def test_inline_suppression_silences(self, tmp_path):
        (tmp_path / "c.py").write_text(
            'import urllib.request\n'
            'def go(url):\n'
            '    return urllib.request.urlopen(url)'
            '  # xgtpu: disable=XGT012\n')
        act, sup = run_codes(tmp_path, {"XGT012"})
        assert not act and len(sup) == 1

    def test_repo_has_no_timeoutless_client(self):
        """Acceptance: the XGT012 debt is zero — every outbound HTTP
        call in the package + tools passes an explicit timeout (the
        baseline stays empty)."""
        facts = default_engine([PKG_DIR]).facts()
        assert facts.http_calls, "no HTTP client facts extracted"
        missing = [(f, c, ln) for f, c, ln, ht in facts.http_calls
                   if not ht]
        assert not missing, missing


# ------------------------------------------------------------------ XGT011
def lock_tree(order_m2: str) -> str:
    return (
        'import threading\n'
        'class A:\n'
        '    def __init__(self):\n'
        '        self._lock_a = threading.Lock()\n'
        '        self._lock_b = threading.Lock()\n'
        '    def m1(self):\n'
        '        with self._lock_a:\n'
        '            with self._lock_b:\n'
        '                pass\n'
        '    def m2(self):\n' + order_m2)


class TestLockOrderGraph:
    CONSISTENT = ('        with self._lock_a:\n'
                  '            with self._lock_b:\n'
                  '                pass\n')
    INVERTED = ('        with self._lock_b:\n'
                '            with self._lock_a:\n'
                '                pass\n')

    def test_consistent_order_is_clean(self, tmp_path):
        (tmp_path / "locks.py").write_text(lock_tree(self.CONSISTENT))
        act, _ = run_codes(tmp_path, {"XGT011"})
        assert not act, messages(act)

    def test_reordered_pair_fires(self, tmp_path):
        """Acceptance negative #3: reordering one nested lock pair
        produces a cycle finding."""
        (tmp_path / "locks.py").write_text(lock_tree(self.INVERTED))
        act, _ = run_codes(tmp_path, {"XGT011"})
        assert len(act) == 1 and "lock-order cycle" in act[0].message
        assert "A._lock_a" in act[0].message
        assert "A._lock_b" in act[0].message

    def test_multi_item_with_orders_left_to_right(self, tmp_path):
        src = ('import threading\n'
               'class B:\n'
               '    def one(self):\n'
               '        with self._l1, self._l2_lock:\n'
               '            pass\n'
               '    def two(self):\n'
               '        with self._l2_lock:\n'
               '            with self._l1_lock:\n'
               '                pass\n')
        # _l1 is not lock-named -> only the _l2_lock edge family exists,
        # and no cycle forms
        (tmp_path / "locks.py").write_text(src)
        act, _ = run_codes(tmp_path, {"XGT011"})
        assert not act
        src2 = ('import threading\n'
                'class C:\n'
                '    def one(self):\n'
                '        with self._l1_lock, self._l2_lock:\n'
                '            pass\n'
                '    def two(self):\n'
                '        with self._l2_lock:\n'
                '            with self._l1_lock:\n'
                '                pass\n')
        (tmp_path / "locks.py").write_text(src2)
        act, _ = run_codes(tmp_path, {"XGT011"})
        assert len(act) == 1 and "lock-order cycle" in act[0].message

    def test_three_node_cycle_reports_without_crashing(self, tmp_path):
        # review r8: the anchor must come from REAL edges — a cycle
        # whose direction disagrees with sorted node order used to hit
        # min() over an empty sequence exactly when a deadlock existed
        src = ('import threading\n'
               'class A:\n'
               '    def m1(self):\n'
               '        with self._a_lock:\n'
               '            with self._c_lock:\n'
               '                pass\n'
               '    def m2(self):\n'
               '        with self._c_lock:\n'
               '            with self._b_lock:\n'
               '                pass\n'
               '    def m3(self):\n'
               '        with self._b_lock:\n'
               '            with self._a_lock:\n'
               '                pass\n')
        (tmp_path / "locks.py").write_text(src)
        act, _ = run_codes(tmp_path, {"XGT011"})
        assert len(act) == 1 and "lock-order cycle" in act[0].message
        assert "A._a_lock -> A._c_lock" in act[0].message
        assert act[0].path.endswith("locks.py") and act[0].line > 0

    def test_repo_graph_has_edges_and_no_cycles(self):
        eng = ContractEngine(REPO_ROOT, fact_paths=[PKG_DIR],
                             codes={"XGT011"})
        facts = eng.facts()
        edges = {(o, i) for _, o, i, _ in facts.lock_edges}
        # the two-lock classes the ISSUE names must be in the graph
        assert ("FeatureStore._put_lock", "FeatureStore._lock") in edges
        act, _ = eng.run()
        assert not [f for f in act if f.rule == "XGT011"], messages(act)


# ----------------------------------------------------------- inventory
class TestInventory:
    def _mini_tree(self, tmp_path):
        (tmp_path / "server.py").write_text(SERVER_SRC)
        (tmp_path / "m.py").write_text(
            'import os, threading, urllib.request\n'
            'c = Counter("xgbtpu_foo_total", "h")\n'
            'v = os.environ.get("XGBTPU_FIX_KNOB")\n'
            'def probe(url):\n'
            '    return urllib.request.urlopen(url + "/healthz",\n'
            '                                  timeout=2.0)\n'
            'class A:\n'
            '    def m(self):\n'
            '        with self._a_lock:\n'
            '            with self._b_lock:\n'
            '                pass\n')
        (tmp_path / "OBSERVABILITY.md").write_text(
            DOC_HEADER + "| `xgbtpu_foo_total` | counter |\n")
        (tmp_path / "README.md").write_text("| `XGBTPU_FIX_KNOB` | x |\n")

    def test_roundtrip(self, tmp_path):
        self._mini_tree(tmp_path)
        eng = engine_for(tmp_path)
        out = eng.write_inventory()
        with open(out) as f:
            committed = json.load(f)
        assert committed == eng.inventory()
        assert committed["http_routes"]
        assert committed["metric_families"] == {
            "xgbtpu_foo_total": {"label": None}}
        assert committed["env_knobs"] == ["XGBTPU_FIX_KNOB"]
        assert committed["lock_edges"] == [["A._a_lock", "A._b_lock"]]
        # a fresh engine over the same tree sees no drift
        act, _ = engine_for(tmp_path).run()
        assert not act, messages(act)

    @pytest.mark.parametrize("section,rule", [
        ("http_routes", "XGT008"), ("metric_families", "XGT009"),
        ("env_knobs", "XGT010"), ("lock_edges", "XGT011"),
        ("http_clients", "XGT012")])
    def test_drift_detection_per_section(self, tmp_path, section, rule):
        self._mini_tree(tmp_path)
        eng = engine_for(tmp_path)
        path = eng.write_inventory()
        with open(path) as f:
            data = json.load(f)
        data[section] = []
        with open(path, "w") as f:
            json.dump(data, f)
        act, _ = engine_for(tmp_path).run()
        hits = [f for f in act if f.rule == rule
                and section in f.message]
        assert hits, messages(act)
        assert hits[0].path.endswith("ANALYSIS_CONTRACTS.json")

    def test_committed_inventory_is_fresh_and_nonempty(self):
        """Acceptance: ANALYSIS_CONTRACTS.json is committed, matches
        the tree's extraction, and every inventory is non-empty."""
        path = os.path.join(REPO_ROOT, "ANALYSIS_CONTRACTS.json")
        assert os.path.exists(path), "ANALYSIS_CONTRACTS.json missing"
        with open(path) as f:
            committed = json.load(f)
        eng = default_engine([PKG_DIR])
        assert committed == eng.inventory(), (
            "committed ANALYSIS_CONTRACTS.json is stale — regenerate "
            "with tools/xgtpu_lint.py --write-contracts")
        assert committed["http_routes"]
        assert committed["metric_families"]
        assert committed["env_knobs"]
        assert committed["lock_edges"]
        assert committed["cli_params"]["serve"]
        assert committed["cli_params"]["fleet"]
        assert committed["http_clients"]
        # the committed proof the tree has no timeout-less client
        assert all(e["timeout"] for e in committed["http_clients"])


# ---------------------------------------------------------- enforcement
def test_contract_tree_is_clean():
    """THE tier-1 gate for XGT008-XGT011: the whole repo (package +
    tools + docs + committed inventory) is contract-clean."""
    eng = default_engine([PKG_DIR])
    act, _ = eng.run()
    assert not act, (
        f"xgtpu-lint contracts found {len(act)} violation(s):\n"
        + messages(act))


def test_cli_default_invocation_runs_contracts_clean(capsys):
    assert lint_main(["--rules", ",".join(CONTRACT_CODES)]) == 0


def test_cli_changed_mode_works():
    # facts collect repo-wide; a clean tree stays clean however the
    # reporting is narrowed
    assert lint_main(["--changed", "HEAD"]) == 0


def test_cli_changed_refuses_write_baseline(tmp_path, capsys):
    # review r8: a narrowed-reporting scan must not rewrite the ledger
    assert lint_main(["--changed", "HEAD", "--write-baseline",
                      "--baseline", str(tmp_path / "b.json")]) == 2


def test_changed_mode_reports_doc_anchored_drift_from_py_edit(capsys):
    """Review r8: drift CAUSED by a changed .py file anchors in the
    unchanged doc/inventory surfaces — the pre-commit loop must not
    drop those findings or it passes on exactly the drift the change
    introduced.  An untracked package file constructing an
    undocumented family must surface BOTH the code-anchored XGT009
    finding and the inventory-staleness finding anchored at the
    (unchanged) ANALYSIS_CONTRACTS.json."""
    probe = os.path.join(PKG_DIR, "_contract_probe_tmp.py")
    try:
        with open(probe, "w") as f:
            f.write('c = Counter("xgbtpu_probe_only_total", "h")\n')
        rc = lint_main(["--changed", "HEAD"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "xgbtpu_probe_only_total" in out
        assert "ANALYSIS_CONTRACTS.json" in out
    finally:
        os.remove(probe)


def test_write_baseline_subset_scan_keeps_per_file_debt_elsewhere(
        tmp_path):
    """Review r8: the contract engine's repo-wide fact scope must NOT
    leak into the per-file rescope coverage — a subdirectory
    --write-baseline has to keep accepted per-file debt outside the
    scanned subset (while contract entries DO rescope repo-wide)."""
    from xgboost_tpu.analysis import Baseline
    bpath = str(tmp_path / "b.json")
    Baseline({
        "XGT006|xgboost_tpu/learner.py|fake = time.time() - t0": 1,
        "XGT010|README.md|`XGBTPU_GONE` stale row": 1,
    }).dump(bpath)
    assert lint_main([os.path.join(PKG_DIR, "serving"),
                      "--write-baseline", "--baseline", bpath]) == 0
    merged = Baseline.load(bpath)
    # per-file debt outside the scanned subset survives...
    assert any(k.startswith("XGT006|") for k in merged.counts), (
        merged.counts)
    # ...while the stale contract entry was re-scoped away (contract
    # findings re-collect repo-wide and this one no longer exists)
    assert not any(k.startswith("XGT010|") for k in merged.counts), (
        merged.counts)


# ------------------------------------------------- runtime cross-check
def _static_lock_edges():
    eng = ContractEngine(REPO_ROOT, fact_paths=[PKG_DIR])
    return {(o, i) for _, o, i, _ in eng.facts().lock_edges}


def _parse_instrumented(name: str):
    """'FeatureStore#1._put_lock' -> ('FeatureStore', '_put_lock')."""
    cls, _, attr = name.partition("#")
    return cls, attr.split(".", 1)[1]


def test_runtime_lock_orders_covered_by_static_graph(lock_race_checker):
    """The ISSUE's cross-check: stress FeatureStore (two-lock
    put/gather staging) and Membership under the runtime
    LockRaceChecker — no violations — and every same-class lock ORDER
    the runtime checker observes must be an edge of the static XGT011
    graph (the static rule subsumes what the dynamic one happened to
    see)."""
    from xgboost_tpu.fleet.membership import Membership
    from xgboost_tpu.serving.featurestore import FeatureStore
    import numpy as np

    store = FeatureStore(num_feature=4, budget_mb=0.0001)  # ~6 slots
    lock_race_checker.instrument(
        store, locks=("_lock", "_put_lock"),
        guarded=("_slots", "_free", "_slab"))
    m = Membership(lease_sec=30.0)
    lock_race_checker.instrument(m, locks=("_lock",),
                                 guarded=("_ring_stale",))

    def worker(seed: int):
        rng = random.Random(seed)
        for i in range(30):
            op = rng.randrange(6)
            ids = [f"e{rng.randrange(10)}" for _ in range(2)]
            if op == 0:
                store.put(ids, np.full((2, 4), float(i), np.float32))
            elif op == 1:
                X, missing = store.gather(ids, pad_to=4)
                assert (X is None) == bool(missing)
            elif op == 2:
                store.invalidate(ids if rng.random() < 0.5 else None)
            elif op == 3:
                rid = f"r{rng.randrange(3)}"
                m.register(rid, f"http://127.0.0.1:{9000 + seed}")
                m.heartbeat(rid)
            elif op == 4:
                rep = m.acquire()
                if rep is not None:
                    m.release(rep, ok=bool(rng.randrange(2)))
            else:
                m.route_ids(ids)
                m.deregister(f"r{rng.randrange(3)}")

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    static_edges = _static_lock_edges()
    runtime_same_class = set()
    for a, b in lock_race_checker._edges:
        ca, aa = _parse_instrumented(a)
        cb, ab = _parse_instrumented(b)
        if ca == cb and (ca, aa) != (cb, ab):
            runtime_same_class.add((f"{ca}.{aa}", f"{cb}.{ab}"))
    # the put path's _put_lock -> _lock staging MUST have been seen
    assert ("FeatureStore._put_lock", "FeatureStore._lock") \
        in runtime_same_class
    uncovered = runtime_same_class - static_edges
    assert not uncovered, (
        f"runtime observed lock orders missing from the static XGT011 "
        f"graph: {sorted(uncovered)}")
    # teardown runs lock_race_checker.assert_clean()


# ------------------------------------------------------- route sweep
def _handler_routes():
    """(server_key, method, path) for every route the extractor found
    in the three handler files — the parametrized 404-sweep surface."""
    eng = ContractEngine(REPO_ROOT, fact_paths=[
        os.path.join(PKG_DIR, "serving"),
        os.path.join(PKG_DIR, "fleet"),
        os.path.join(PKG_DIR, "obs")])
    keymap = {"xgboost_tpu/serving/http.py": "serving",
              "xgboost_tpu/fleet/router.py": "router",
              "xgboost_tpu/obs/server.py": "obs"}
    out = set()
    for f, _cls, method, path, _ in eng.facts().routes:
        rel = os.path.relpath(f, REPO_ROOT).replace(os.sep, "/")
        key = keymap.get(rel)
        if key:
            out.add((key, method, path))
    assert len(out) >= 20, sorted(out)
    return sorted(out)


ROUTES = _handler_routes()


@pytest.fixture(scope="module")
def servers():
    """One live instance of each HTTP tier: replica server (tiny
    model), fleet router (no replicas), obs metrics server."""
    import numpy as np
    import xgboost_tpu as xgb
    from xgboost_tpu.fleet.router import FleetRouter
    from xgboost_tpu.obs.server import MetricsServer
    from xgboost_tpu.serving.http import run_server
    import tempfile

    work = tempfile.mkdtemp(prefix="xgbtpu_routes_")
    rng = np.random.RandomState(0)
    X = rng.rand(80, 4).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 2,
                     "eta": 0.4, "silent": 1},
                    xgb.DMatrix(X, label=y), 2)
    path = os.path.join(work, "m.bin")
    bst.save_model(path)
    srv = run_server(path, port=0, min_bucket=8, max_bucket=16,
                     max_wait_ms=1, poll_sec=0, warmup=False,
                     quiet=True, block=False)
    router = FleetRouter(port=0, hc_sec=0, quiet=True).start()
    mets = MetricsServer(port=0)
    try:
        yield {"serving": ("127.0.0.1", srv.port),
               "router": ("127.0.0.1", router.port),
               "obs": ("127.0.0.1", mets.port)}
    finally:
        srv.shutdown()
        router.shutdown()
        mets.stop()
        shutil.rmtree(work, ignore_errors=True)


def _req(addr, method, path):
    import http.client
    conn = http.client.HTTPConnection(*addr, timeout=10)
    try:
        body = b"" if method == "POST" else None
        conn.request(method, path, body=body)
        r = conn.getresponse()
        raw = r.read()
        try:
            payload = json.loads(raw)
        except ValueError:
            payload = {}
        return r.status, payload
    finally:
        conn.close()


@pytest.mark.parametrize("key,method,path", ROUTES)
def test_extracted_routes_are_served(servers, key, method, path):
    """Every route the static extractor found answers as a KNOWN
    route: whatever the status (200/400/404-disabled/409/503), the
    body is never the no-route 404 — the extractor and the dispatch
    are the same table."""
    status, payload = _req(servers[key], method, path)
    err = payload.get("error", "") if isinstance(payload, dict) else ""
    assert not err.startswith("no route"), (method, path, status, err)


def test_unknown_routes_404_with_consistent_json_body(servers):
    """The round-8 sweep contract: all three servers reject unknown
    paths — both methods — with the SAME JSON 404 body shape."""
    for key, addr in servers.items():
        for method in ("GET", "POST"):
            status, payload = _req(addr, method, "/definitely/not/here")
            assert status == 404, (key, method, status)
            assert payload.get("error") == \
                "no route /definitely/not/here", (key, method, payload)

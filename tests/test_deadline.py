"""End-to-end deadline discipline (reliability/deadline.py; ISSUE 10).

Acceptance criteria covered here:
(a) an EXPIRED request is rejected before any device work, at both the
    router and the replica, counter-verified on
    ``xgbtpu_deadline_rejected_total``;
(b) the router and replica share ONE ``X-Deadline-Ms`` contract: the
    router stamps the REMAINING budget onto the replica hop (never the
    original), and restamps on the retry;
(c) the MicroBatcher drops expired entries pre-dispatch
    (``xgbtpu_deadline_dropped_total``) and the caller sees the typed
    :class:`DeadlineExceeded` (HTTP 504), never a late result;
(d) replica admission-by-service-time: a budget below the bucket's
    observed service EWMA is 504'd up front;
(e) the retry path spends the remaining budget with jittered backoff
    instead of arming a fresh timeout.

All tests are mesh-free (stdlib HTTP + tiny CPU models) — no
``sharding.AxisType`` dependency.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.fleet import FleetRouter
from xgboost_tpu.profiling import reliability_metrics
from xgboost_tpu.reliability.deadline import (DEADLINE_HEADER, Deadline,
                                              DeadlineExceeded,
                                              backoff_delay, jittered)
from xgboost_tpu.serving import run_server
from xgboost_tpu.serving.batcher import MicroBatcher


# ------------------------------------------------------------------ unit
def test_deadline_budget_spends_down():
    dl = Deadline(10_000)
    assert not dl.expired()
    assert 0 < dl.remaining() <= 10.0
    r0 = dl.remaining()
    time.sleep(0.02)
    assert dl.remaining() < r0  # monotonic spend-down
    assert Deadline(0).expired()


def test_deadline_header_roundtrip_carries_remaining():
    dl = Deadline(5_000)
    time.sleep(0.05)
    hop = Deadline.from_header(dl.header_value())
    # the hop sees the REMAINING budget, not the original
    assert hop is not None
    assert hop.remaining_ms() <= dl.remaining_ms() + 1.0 < 5_000


@pytest.mark.parametrize("bad", [None, "", "nan-ish", "-5"])
def test_deadline_unparseable_header_means_no_deadline(bad):
    assert Deadline.from_header(bad) is None


def test_jittered_stays_in_band():
    vals = [jittered(1.0) for _ in range(200)]
    assert all(0.8 <= v <= 1.2 for v in vals)
    assert len({round(v, 6) for v in vals}) > 1, "no jitter at all"


def test_backoff_delay_bounded_by_deadline():
    assert backoff_delay(1) <= 0.05
    # an almost-spent budget caps the sleep at a quarter of what's left
    dl = Deadline(40)
    assert backoff_delay(1, deadline=dl) <= dl.remaining() * 0.25 + 1e-6
    assert backoff_delay(3, base=10.0, cap=2.0) <= 2.0


# --------------------------------------------------------------- batcher
def test_batcher_flush_drops_expired_entry_pre_dispatch():
    """(c) the worker's flush skips an expired-deadline entry BEFORE
    dispatch: its rows never reach the predict fn, its caller gets the
    typed error, the drop counts — while live batch-mates still run."""
    rm = reliability_metrics()
    seen_rows = []

    def predict(X, output_margin=False):
        seen_rows.append(int(X.shape[0]))
        return np.zeros(X.shape[0], np.float32)

    b = MicroBatcher(predict, max_wait_ms=1.0, max_batch_rows=8)
    from xgboost_tpu.serving.batcher import _Request
    try:
        base_dropped = rm.deadline_dropped.value
        live = _Request(np.zeros((1, 3), np.float32), False)
        dead = _Request(np.zeros((2, 3), np.float32), False,
                        deadline=Deadline(0))
        with b._lock:
            b._queued_rows += 3
        b._flush([live, dead])
        assert live.done.is_set() and live.error is None
        assert live.result.shape == (1,)
        assert dead.done.is_set()
        assert isinstance(dead.error, DeadlineExceeded)
        assert rm.deadline_dropped.value == base_dropped + 1
        assert seen_rows == [1], "expired rows reached the device fn"
    finally:
        b.close()


def test_batcher_caller_sees_typed_error_when_budget_dies_queued():
    """(c) integration: a caller whose budget dies while its request
    waits behind a parked batch gets DeadlineExceeded (504 upstream),
    never a late result."""
    gate = threading.Event()

    def predict(X, output_margin=False):
        gate.wait(5.0)  # first batch parks the worker
        return np.zeros(X.shape[0], np.float32)

    b = MicroBatcher(predict, max_wait_ms=1.0, max_batch_rows=4)
    try:
        t1 = threading.Thread(
            target=lambda: b.submit(np.zeros((1, 3), np.float32)))
        t1.start()
        time.sleep(0.05)  # worker is now parked inside predict()
        with pytest.raises(DeadlineExceeded):
            b.submit(np.zeros((2, 3), np.float32), deadline=Deadline(80))
        gate.set()
        t1.join(5.0)
    finally:
        gate.set()
        b.close()


def test_batcher_submit_within_budget_succeeds():
    b = MicroBatcher(lambda X, output_margin=False:
                     np.ones(X.shape[0], np.float32), max_wait_ms=0.5)
    try:
        out = b.submit(np.zeros((3, 2), np.float32),
                       deadline=Deadline(10_000))
        assert out.shape == (3,)
    finally:
        b.close()


# --------------------------------------------------------------- replica
def _train_model(path, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(200, 5).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 0.4, "silent": 1},
                    xgb.DMatrix(X, label=y), 3)
    bst.save_model(path)
    return X


def _post(url, data=b"", headers=None):
    req = urllib.request.Request(url, data=data, method="POST",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            return e.code, json.loads(raw)
        except ValueError:
            return e.code, {}


@pytest.fixture(scope="module")
def replica(tmp_path_factory):
    d = tmp_path_factory.mktemp("deadline")
    path = str(d / "m.bin")
    X = _train_model(path)
    srv = run_server(path, port=0, min_bucket=8, max_bucket=32,
                     max_wait_ms=1.0, poll_sec=0, warmup=False,
                     quiet=True, block=False)
    yield srv, X
    srv.shutdown()


def _csv(rows):
    return "\n".join(",".join(f"{v:.6f}" for v in row)
                     for row in rows).encode()


def test_replica_rejects_expired_before_any_work(replica):
    """(a) X-Deadline-Ms: 0 -> 504 up front: no rows parsed, no batch
    submitted, counter bumped."""
    srv, X = replica
    rm = reliability_metrics()
    base = rm.deadline_rejected.value
    batches = srv.metrics.batches.value
    st, js = _post(f"http://{srv.host}:{srv.port}/predict",
                   data=_csv(X[:2]), headers={DEADLINE_HEADER: "0"})
    assert st == 504 and js["deadline_exceeded"] is True
    assert rm.deadline_rejected.value == base + 1
    assert srv.metrics.batches.value == batches, "device work was paid"
    # same discipline on the by-id route
    st, js = _post(f"http://{srv.host}:{srv.port}/predict_by_id",
                   data=b'{"ids": ["x"]}', headers={DEADLINE_HEADER: "0"})
    assert st == 504 and js["deadline_exceeded"] is True


def test_replica_generous_deadline_serves_normally(replica):
    srv, X = replica
    st, js = _post(f"http://{srv.host}:{srv.port}/predict",
                   data=_csv(X[:2]),
                   headers={DEADLINE_HEADER: "30000"})
    assert st == 200 and js["rows"] == 2


def test_replica_admission_by_observed_service_time(replica):
    """(d) remaining budget below the bucket's service EWMA -> 504
    BEFORE submit; the estimate recovers as real traffic lands."""
    srv, X = replica
    rm = reliability_metrics()
    # poison the 2-row bucket's estimate: pretend it takes ~10 s
    # (folded in repeatedly — earlier tests seeded a fast EWMA)
    for _ in range(8):
        srv.observe_service(2, 10.0)
    assert srv.service_estimate(2) >= 5.0
    base = rm.deadline_rejected.value
    st, js = _post(f"http://{srv.host}:{srv.port}/predict",
                   data=_csv(X[:2]),
                   headers={DEADLINE_HEADER: "200"})
    assert st == 504 and "service time" in js["error"]
    assert rm.deadline_rejected.value == base + 1
    # deadline-less traffic is never admission-gated, and its real
    # latency pulls the EWMA back down
    for _ in range(40):
        st, _ = _post(f"http://{srv.host}:{srv.port}/predict",
                      data=_csv(X[:2]))
        assert st == 200
    assert srv.service_estimate(2) < 1.0
    st, _ = _post(f"http://{srv.host}:{srv.port}/predict",
                  data=_csv(X[:2]), headers={DEADLINE_HEADER: "5000"})
    assert st == 200


def test_service_estimate_bucketing():
    from xgboost_tpu.serving.http import PredictServer
    assert PredictServer._svc_bucket(1) == 1
    assert PredictServer._svc_bucket(2) == 2
    assert PredictServer._svc_bucket(3) == 4
    assert PredictServer._svc_bucket(1000) == 1024


# ---------------------------------------------------------------- router
class _EchoStub:
    """Stub replica recording the deadline header of every /predict it
    receives; optionally fails the first N requests (retry testing)."""

    def __init__(self):
        self.headers_seen = []
        self.fail_next = 0
        self.delay = 0.0
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def log_message(self, *a):
                pass

            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._send(200, {"status": "ok", "state": "serving",
                                 "model_hash": "stub"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                stub.headers_seen.append(
                    self.headers.get(DEADLINE_HEADER))
                if stub.delay:
                    time.sleep(stub.delay)
                if stub.fail_next > 0:
                    stub.fail_next -= 1
                    self._send(500, {"error": "injected"})
                    return
                self._send(200, {"predictions": [0.5], "rows": 1,
                                 "model_version": 1})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _register(base, rid, stub):
    st, js = _post(base + "/fleet/register",
                   data=json.dumps({"replica_id": rid,
                                    "url": stub.url}).encode())
    assert st == 200, js


def test_router_rejects_expired_and_stamps_remaining_budget():
    """(a)+(b) the router 504s an expired request before any dispatch,
    and stamps the REMAINING (shrunken) budget onto the replica hop."""
    rt = FleetRouter(port=0, hc_sec=0, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    stub = _EchoStub()
    rm = reliability_metrics()
    try:
        _register(base, "r1", stub)
        n_rejected = rm.deadline_rejected.value
        st, js = _post(base + "/predict", data=b"0.5",
                       headers={DEADLINE_HEADER: "0"})
        assert st == 504 and js["deadline_exceeded"] is True
        assert rm.deadline_rejected.value == n_rejected + 1
        assert stub.headers_seen == [], "expired request was dispatched"
        # a live budget is forwarded, shrunk by router time
        st, js = _post(base + "/predict", data=b"0.5",
                       headers={DEADLINE_HEADER: "5000"})
        assert st == 200
        assert len(stub.headers_seen) == 1
        fwd = float(stub.headers_seen[0])
        assert 0 < fwd <= 5000
        # no client deadline + no fleet_deadline_ms default -> no stamp
        st, _ = _post(base + "/predict", data=b"0.5")
        assert st == 200 and stub.headers_seen[1] is None
    finally:
        stub.close()
        rt.shutdown()


def test_router_default_deadline_and_budgeted_retry():
    """(e) fleet_deadline_ms stamps a default budget, and the
    retry-once hop is restamped with what REMAINS after the failed
    first attempt + backoff."""
    rt = FleetRouter(port=0, hc_sec=0, deadline_ms=2000.0,
                     quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    ok = _EchoStub()
    bad = _EchoStub()
    bad.fail_next = 10_000
    try:
        _register(base, "a-bad", bad)
        _register(base, "b-ok", ok)
        # both stubs idle -> least-loaded picks "a-bad" first (id
        # tiebreak), fails, retries on "b-ok" with a restamped budget
        st, js = _post(base + "/predict", data=b"0.5")
        assert st == 200, js
        assert len(ok.headers_seen) == 1
        first = float(bad.headers_seen[0])
        second = float(ok.headers_seen[0])
        assert 0 < first <= 2000.0
        assert 0 < second < first, (
            "retry hop did not spend the remaining budget")
    finally:
        ok.close()
        bad.close()
        rt.shutdown()


def test_budget_cut_hop_is_504_and_never_charges_the_breaker():
    """A hop cut short by the request's own budget (deadline-shrunk
    socket timeout) is the REQUEST running out of money, not a replica
    failure: the router answers 504 and the breaker stays closed —
    tight-budget clients must not 503 a healthy replica for everyone
    else."""
    rt = FleetRouter(port=0, hc_sec=0, breaker_failures=2,
                     quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    slow = _EchoStub()
    slow.delay = 0.4  # healthy, just slower than the clients' budgets
    try:
        _register(base, "r1", slow)
        for _ in range(4):
            st, js = _post(base + "/predict", data=b"0.5",
                           headers={DEADLINE_HEADER: "120"})
            assert st == 504, js
            assert js["deadline_exceeded"] is True
        members = _get(base + "/fleet/members")["replicas"]
        r1 = [m for m in members if m["replica_id"] == "r1"][0]
        assert r1["breaker"] == "closed", \
            "tight-budget timeouts tripped the breaker"
        assert r1["consecutive_failures"] == 0
        assert r1["outstanding"] == 0  # neutral releases balanced
        # a patient client is still served by the same replica
        st, js = _post(base + "/predict", data=b"0.5",
                       headers={DEADLINE_HEADER: "20000"})
        assert st == 200, js
    finally:
        slow.close()
        rt.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def test_router_deadline_spent_mid_retry_is_504():
    """A first attempt that eats the whole budget leaves nothing to
    retry with: the router answers 504, not a fresh-timeout retry."""
    rt = FleetRouter(port=0, hc_sec=0, quiet=True).start()
    base = f"http://{rt.host}:{rt.port}"
    slow_bad = _EchoStub()
    slow_bad.delay = 0.3
    slow_bad.fail_next = 10_000
    ok = _EchoStub()
    try:
        _register(base, "a-slowbad", slow_bad)
        _register(base, "b-ok", ok)
        st, js = _post(base + "/predict", data=b"0.5",
                       headers={DEADLINE_HEADER: "150"})
        assert st == 504, js
        assert js["deadline_exceeded"] is True
        assert ok.headers_seen == [], "retry fired with a dead budget"
    finally:
        slow_bad.close()
        ok.close()
        rt.shutdown()

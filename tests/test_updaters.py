"""Updater tests: exact colmaker, prune, refresh, distcol (reference
updater registry src/tree/updater.cpp:18-31)."""

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu.models.updaters import parse_updaters, prune_tree


def make_data(n=2000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = ((X[:, 0] > 0.5) ^ (X[:, 1] > 0.3)).astype(np.float32)
    return X, y


def test_parse_updaters_rejects_unknown():
    assert parse_updaters("grow_histmaker,prune") == ("grow_histmaker",
                                                     "prune")
    with pytest.raises(ValueError):
        parse_updaters("grow_gpu")


# ------------------------------------------------------------ exact greedy
def test_colmaker_exact_beats_coarse_hist():
    """With very coarse quantile bins a fine threshold is unfindable;
    exact enumeration of all distinct values must find it."""
    rng = np.random.RandomState(5)
    n = 3000
    X = np.round(rng.rand(n, 3), 3).astype(np.float32)
    y = (X[:, 0] > 0.777).astype(np.float32)
    params_exact = {"objective": "binary:logistic", "max_depth": 2,
                    "eta": 1.0, "updater": "grow_colmaker,prune"}
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train(params_exact, d, 2, verbose_eval=False)
    err = ((bst.predict(d) > 0.5) != (y > 0.5)).mean()
    assert err < 0.005
    # the exact split threshold is a distinct data value near 0.777
    dump = bst.get_dump()[0]
    first_cond = float(dump.split("<")[1].split("]")[0])
    assert abs(first_cond - 0.777) < 0.002


def test_colmaker_matches_histmaker_on_binary_features():
    """On 0/1 features, 256-bin histogram == exact enumeration: the two
    updaters must produce identical models."""
    rng = np.random.RandomState(6)
    X = (rng.rand(1000, 10) > 0.5).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + X[:, 2] > 0.5).astype(np.float32)
    p = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.7}
    d1, d2 = xgb.DMatrix(X, label=y), xgb.DMatrix(X, label=y)
    bst_h = xgb.train({**p, "updater": "grow_histmaker,prune"}, d1, 3,
                      verbose_eval=False)
    bst_c = xgb.train({**p, "updater": "grow_colmaker,prune"}, d2, 3,
                      verbose_eval=False)
    np.testing.assert_allclose(bst_h.predict(d1), bst_c.predict(d2),
                               rtol=1e-5)


# ------------------------------------------------------------------- prune
def test_prune_tree_removes_weak_leaf_pair():
    X, y = make_data()
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 5,
                     "eta": 0.5}, d, 1, verbose_eval=False)
    tree = bst.gbtree.trees[0]
    gains = np.asarray(tree.gain)
    pos_gains = gains[gains > 0]
    gamma = float(np.percentile(pos_gains, 60))
    pruned, resolve = prune_tree(tree, gamma)
    n_splits_before = int((np.asarray(tree.feature) >= 0).sum())
    n_splits_after = int((np.asarray(pruned.feature) >= 0).sum())
    assert n_splits_after < n_splits_before
    # surviving split nodes that kept both children as leaves have
    # gain >= gamma
    f = np.asarray(pruned.feature)
    il = np.asarray(pruned.is_leaf)
    g = np.asarray(pruned.gain)
    n = len(f)
    for nid in range(n):
        if f[nid] >= 0 and not il[nid]:
            l, r = 2 * nid + 1, 2 * nid + 2
            def leaflike(c):
                return c >= n or il[c] or f[c] < 0
            if leaflike(l) and leaflike(r):
                assert g[nid] >= gamma
    # resolve maps pruned descendants to their surviving ancestor
    for nid in range(1, n):
        assert il[resolve[nid]] or f[resolve[nid]] < 0 or resolve[nid] == nid


def test_gamma_post_prune_keeps_strong_grandchildren():
    """XOR data: the root split has ~zero gain but its children's splits
    are strong.  Post-pruning (reference semantics) must KEEP the tree;
    pre-pruning would collapse it to a stump."""
    rng = np.random.RandomState(7)
    X = (rng.rand(4000, 2) > 0.5).astype(np.float32)
    y = (X[:, 0] != X[:, 1]).astype(np.float32)  # pure XOR
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 2,
                     "eta": 1.0, "gamma": 0.5}, d, 1, verbose_eval=False)
    err = ((bst.predict(d) > 0.5) != (y > 0.5)).mean()
    assert err < 0.01  # gamma=0.5 did not destroy the XOR tree


def test_gamma_prunes_noise_splits():
    rng = np.random.RandomState(8)
    X = rng.rand(2000, 5).astype(np.float32)
    y = rng.rand(2000).astype(np.float32)  # pure noise
    d = xgb.DMatrix(X, label=y)
    bst_free = xgb.train({"objective": "reg:linear", "max_depth": 5,
                          "eta": 0.3}, d, 1, verbose_eval=False)
    d2 = xgb.DMatrix(X, label=y)
    bst_g = xgb.train({"objective": "reg:linear", "max_depth": 5,
                       "eta": 0.3, "gamma": 10.0}, d2, 1, verbose_eval=False)
    splits_free = int((np.asarray(bst_free.gbtree.trees[0].feature) >= 0).sum())
    splits_g = int((np.asarray(bst_g.gbtree.trees[0].feature) >= 0).sum())
    assert splits_g < splits_free


# ----------------------------------------------------------------- refresh
def test_refresh_recomputes_leaves_on_new_data():
    X1, y1 = make_data(seed=1)
    X2 = X1.copy()
    y2 = 1.0 - y1  # flipped labels: leaf values must flip sign
    d1 = xgb.DMatrix(X1, label=y1)
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.5}
    bst = xgb.train(params, d1, 3, verbose_eval=False)
    structure_before = [np.asarray(t.feature).copy()
                        for t in bst.gbtree.trees]
    acc_before = (((bst.predict(xgb.DMatrix(X2)) > 0.5) == (y2 > 0.5))
                  .mean())

    d2 = xgb.DMatrix(X2, label=y2)
    bst.set_param("updater", "refresh")
    # each refresh is one damped Newton replacement of all leaf values at
    # the current margin (all trees share one gradient snapshot, like the
    # reference); iterate to converge on the flipped labels
    for i in range(8):
        bst.update(d2, i)
    # structure unchanged
    for t, f_before in zip(bst.gbtree.trees, structure_before):
        np.testing.assert_array_equal(np.asarray(t.feature), f_before)
    acc_after = ((bst.predict(xgb.DMatrix(X2)) > 0.5) == (y2 > 0.5)).mean()
    assert acc_before < 0.5 and acc_after > 0.85


def test_refresh_stats_are_exact():
    """Refreshed node stats must equal the data's gradient statistics at
    the pre-refresh margin: root sum_hess == sum of p(1-p), and each
    refreshed root-level leaf weight follows -G/(H+lambda) * eta."""
    X, y = make_data(seed=2)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 0.5}, d, 2, verbose_eval=False)
    p = bst.predict(xgb.DMatrix(X))  # pre-refresh probabilities
    bst.set_param("updater", "refresh")
    bst.update(d, 0)
    expected_hess = float(np.sum(p * (1.0 - p)))
    for t in bst.gbtree.trees:
        root_hess = float(np.asarray(t.sum_hess)[0])
        np.testing.assert_allclose(root_hess, expected_hess, rtol=1e-4)
    # root would-be leaf weight: -G/(H+lambda) * eta with G = sum(p - y)
    G = float(np.sum(p - y))
    w = -G / (expected_hess + 1.0) * 0.5
    np.testing.assert_allclose(
        float(np.asarray(bst.gbtree.trees[0].leaf_value)[0]), w, rtol=1e-4)


def test_skmaker_trains_and_differs_from_histmaker():
    """grow_skmaker (models/skmaker.py): per-node 3-way sketch split
    selection — must train to a good model (lossier than histograms is
    acceptable, reference skmaker is approximate by design) and must
    actually use the sketch finder (distinct trees from histmaker with
    a coarse sketch; guards against silently falling through to the
    histogram path)."""
    import xgboost_tpu as xgb

    rng = np.random.RandomState(0)
    X = rng.rand(3000, 8).astype(np.float32)
    y = ((X[:, 0] > 0.5) ^ (X[:, 1] > 0.3)).astype(np.float32)
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.5,
              "sketch_eps": 0.1}
    res = {}
    bst = xgb.train({**params, "updater": "grow_skmaker,refresh"},
                    xgb.DMatrix(X, label=y), 8,
                    evals=[(xgb.DMatrix(X, label=y), "train")],
                    evals_result=res, verbose_eval=False)
    # sketch_eps=0.1 coarsens both binning (~20 cuts) and candidates
    assert float(res["train-error"][-1]) < 0.08
    assert bst.gbtree._split_finder() is not None  # sketch finder active
    state = bst.gbtree.get_state()
    feats = state["tree_feature"]
    assert (feats >= -1).all() and (feats < 8).all()

    bst_h = xgb.train(params, xgb.DMatrix(X, label=y), 8,
                      verbose_eval=False)
    state_h = bst_h.gbtree.get_state()
    assert bst_h.gbtree._split_finder() is None
    # with a 20-candidate sketch vs 67-bin histograms, at least one
    # split decision must differ somewhere in the ensemble
    assert not (np.array_equal(state["tree_cut_index"],
                               state_h["tree_cut_index"])
                and np.array_equal(state["tree_feature"],
                                   state_h["tree_feature"]))


def test_skmaker_coarse_sketch_still_learns():
    """With a very coarse sketch (few candidate cuts) skmaker still
    finds usable splits — the candidate set shrinks, accuracy degrades
    gracefully."""
    import xgboost_tpu as xgb

    rng = np.random.RandomState(1)
    X = rng.rand(2000, 5).astype(np.float32)
    y = (X[:, 2] > 0.6).astype(np.float32)
    res = {}
    xgb.train({"objective": "binary:logistic", "max_depth": 3, "eta": 1.0,
               "updater": "grow_skmaker", "sketch_eps": 0.25},
              xgb.DMatrix(X, label=y), 5,
              evals=[(xgb.DMatrix(X, label=y), "train")],
              evals_result=res, verbose_eval=False)
    assert float(res["train-error"][-1]) < 0.1


def test_multi_root_trees_route_by_root_index():
    """Multi-root trees (reference TreeParam num_roots + BoosterInfo
    root_index, data.h:39-58, model.h:534-543): rows enter the tree at
    their per-row root; each root subtree learns its own regime."""
    import xgboost_tpu as xgb

    rng = np.random.RandomState(7)
    n = 2000
    X = rng.rand(n, 3).astype(np.float32)
    regime = (rng.rand(n) > 0.5).astype(np.uint32)
    # opposite relationships per regime: a single shallow tree cannot
    # capture both, two roots trivially can
    y = np.where(regime == 0, X[:, 0] > 0.5, X[:, 0] <= 0.5).astype(
        np.float32)

    d = xgb.DMatrix(X, label=y)
    d.set_uint_info("root_index", regime)
    params = {"objective": "binary:logistic", "max_depth": 2, "eta": 1.0,
              "num_roots": 2}
    res = {}
    bst = xgb.train(params, d, 3, evals=[(d, "train")], evals_result=res,
                    verbose_eval=False)
    assert res["train-error"][-1] < 0.02, res

    # root routing matters: same features, different root, different leaf
    d2 = xgb.DMatrix(X, label=y)
    d2.set_uint_info("root_index", 1 - regime)  # flip every row's root
    p_flip = bst.predict(d2)
    p_orig = bst.predict(xgb.DMatrix(X, label=y))  # no root -> root 0
    assert float(np.mean((p_flip > 0.5) == y)) < 0.2  # flipped = wrong

    # save/load keeps the multi-root layout working
    import tempfile, os
    fd, path = tempfile.mkstemp(suffix=".model")
    os.close(fd)
    try:
        bst.save_model(path)
        bst2 = xgb.Booster(model_file=path)
        d3 = xgb.DMatrix(X, label=y)
        d3.set_uint_info("root_index", regime)
        p2 = bst2.predict(d3)
        assert float(np.mean((p2 > 0.5) != y)) < 0.02
    finally:
        os.remove(path)

    # dump shows each root's subtree
    dumps = bst.get_dump()
    assert dumps[0].count(":[") >= 2  # at least one split under each root


def test_exact_mode_presence_only_and_agaricus_canonical():
    """Exact mode proposes missing-vs-present splits (the reference's
    end-of-scan candidates) — the ONLY split on presence-only one-hot
    columns — and reproduces the reference's canonical exact-greedy
    agaricus numbers."""
    import xgboost_tpu as xgb

    rng = np.random.RandomState(0)
    n = 2000
    present = rng.rand(n) < 0.5
    X = np.full((n, 2), np.nan, np.float32)
    X[present, 0] = 1.0
    X[:, 1] = rng.rand(n)
    y = present.astype(np.float32)
    r = {}
    xgb.train({"objective": "binary:logistic", "max_depth": 2, "eta": 1.0,
               "updater": "grow_colmaker,prune"},
              xgb.DMatrix(X, label=y), 3,
              evals=[(xgb.DMatrix(X, label=y), "train")], evals_result=r,
              verbose_eval=False)
    assert r["train-error"][-1] < 0.01, r

    dtrain = xgb.DMatrix("/root/reference/demo/data/agaricus.txt.train")
    dtest = xgb.DMatrix("/root/reference/demo/data/agaricus.txt.test",
                        num_col=dtrain.num_col)
    r = {}
    xgb.train({"objective": "binary:logistic", "max_depth": 3, "eta": 1.0,
               "updater": "grow_colmaker,prune"}, dtrain, 2,
              evals=[(dtrain, "train"), (dtest, "test")], evals_result=r,
              verbose_eval=False)
    # the reference CLI's exact-greedy numbers for this config
    assert r["train-error"][0] == pytest.approx(0.014433, abs=2e-6)
    assert r["test-error"][0] == pytest.approx(0.016139, abs=2e-6)
    assert r["train-error"][1] == pytest.approx(0.001228, abs=2e-6)
    assert r["test-error"][1] == 0.0


def test_hist_subtraction_env_gate():
    """Histogram subtraction is env-gated (XGBTPU_HIST_SUBTRACTION=1),
    not a config param (measured ~10x regression on TPU — the public
    surface carries no known-slower knob; advisor round 4).

    The invariant is HISTOGRAM equality (the subtracted sibling differs
    from the direct build only by accumulation-order noise, ~1e-5);
    end-to-end predictions may legitimately diverge when that noise
    crosses a near-tie gain, so the e2e check is metric-level."""
    import os
    import numpy as np
    import jax.numpy as jnp
    import xgboost_tpu as xgb
    from xgboost_tpu.models import tree as T
    from xgboost_tpu.ops.histogram import build_level_histogram

    rng = np.random.RandomState(0)
    N, F, B = 512, 4, 8
    binned = jnp.asarray(rng.randint(0, B, (N, F)).astype(np.uint8))
    gh = jnp.asarray(rng.rand(N, 2).astype(np.float32))
    pos = jnp.asarray((rng.rand(N) < 0.3).astype(np.int32))

    class Cfg:
        n_bin = B
        hist_precision = "fp32"

    hist_parent = build_level_histogram(
        binned, gh, jnp.zeros(N, jnp.int32), 1, B, "fp32")
    h_sub = T._subtracted_level_hist(binned, gh, pos, 2, Cfg, lambda x: x,
                                     hist_parent, jnp.asarray([True]))
    h_full = build_level_histogram(binned, gh, pos, 2, B, "fp32")
    np.testing.assert_allclose(np.asarray(h_sub), np.asarray(h_full),
                               atol=1e-4)

    # e2e: the gated path trains to the same quality; a passed
    # hist_subtraction param is ignored with a warning (still trains)
    X = rng.rand(2000, 6).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.rand(2000) > 0.6).astype(np.float32)

    def logloss(extra_params=None):
        d = xgb.DMatrix(X, label=y)
        p = dict({"objective": "binary:logistic", "max_depth": 4,
                  "eta": 0.3, "silent": 1}, **(extra_params or {}))
        pr = xgb.train(p, d, 5).predict(d)
        eps = 1e-7
        return float(-np.mean(y * np.log(pr + eps)
                              + (1 - y) * np.log(1 - pr + eps)))

    base = logloss()
    os.environ["XGBTPU_HIST_SUBTRACTION"] = "1"
    try:
        sub = logloss()
    finally:
        del os.environ["XGBTPU_HIST_SUBTRACTION"]
    assert abs(base - sub) < 1e-3, (base, sub)
    assert abs(logloss({"hist_subtraction": 1}) - base) < 1e-12
    # silent=0 actually emits the demotion warning (once per process)
    import contextlib
    import io
    from xgboost_tpu.models import gbtree as GB
    GB._WARNED.discard("hist_subtraction")
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        logloss({"hist_subtraction": 1, "silent": 0})
    assert "hist_subtraction is no longer a parameter" in err.getvalue()

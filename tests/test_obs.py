"""Observability layer tests (xgboost_tpu.obs; design in
OBSERVABILITY.md).

Acceptance criteria covered here:
(a) a CLI training run with ``metrics_port=`` exposes live
    ``xgbtpu_training_*`` metrics (scraped over HTTP by a test) and
    with ``obs_log=`` leaves a JSONL timeline that
    ``tools/obs_report.py`` renders into a per-round phase view;
(b) a serving request carrying ``X-Request-Id`` gets the id echoed in
    the response header and appears as a span in the event log;
(c) collective stats exported per rank across real processes
    (``mp_comm_worker.py``), the allreduce count matching the mock
    seam's collective-call count;
(d) the Prometheus exposition output lints (HELP/TYPE per family,
    cumulative histogram buckets ending at ``+Inf == _count``);
(e) ``from xgboost_tpu.profiling import ...`` compat survives.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import xgboost_tpu as xgb
from xgboost_tpu import obs
from xgboost_tpu.obs import comm, trace
from xgboost_tpu.obs.metrics import Histogram, LabeledCounter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def obs_log(tmp_path):
    """Configure a temp event log; always unconfigure (the log is
    process-global and must not leak into other tests)."""
    path = str(tmp_path / "obs.jsonl")
    obs.configure_log(path)
    try:
        yield path
    finally:
        obs.configure_log(None)


def _records(path):
    out = []
    with open(path) as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


def _train(seed=0, rounds=3, n=300, **params):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    p = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.4,
         "silent": 1, "seed": seed, **params}
    return xgb.train(p, xgb.DMatrix(X, label=y), rounds), X, y


# ---------------------------------------------------------- primitives
def test_histogram_quantile_edge_cases():
    h = Histogram("t", buckets=(1.0, 2.0, 4.0))
    # empty: every quantile is 0.0, exactly
    for q in (0.0, 0.5, 1.0):
        assert h.quantile(q) == 0.0
    # single bucket occupied (values in (2.0, 4.0])
    h.observe(3.0)
    h.observe(3.5)
    assert h.quantile(0.0) == 2.0   # lower edge of first nonempty bucket
    assert h.quantile(1.0) == 4.0   # upper edge of last nonempty bucket
    assert 2.0 < h.quantile(0.5) < 4.0
    # q beyond [0,1] clamps to the edges
    assert h.quantile(-1.0) == 2.0
    assert h.quantile(2.0) == 4.0
    # overflow-bucket observations: q=1 reports the top finite bound
    h2 = Histogram("t2", buckets=(1.0,))
    h2.observe(50.0)
    assert h2.quantile(1.0) == 1.0
    assert h2.quantile(0.0) == 1.0  # lower edge of the +Inf bucket


def test_round_profiler_summary_no_division_by_zero():
    from xgboost_tpu.profiling import RoundProfiler
    prof = RoundProfiler(level=0)
    # a round whose phases all measured 0.0s must not raise
    prof.rounds.append({"round": 0, "phases": {"grow": 0.0}, "t0": None})
    s = prof.summary()
    assert "1 rounds" in s and "grow" in s and "0.0%" in s
    # a round with NO phases at all
    prof2 = RoundProfiler(level=0)
    prof2.rounds.append({"round": 0, "phases": {}, "t0": None})
    s2 = prof2.summary()
    assert "1 rounds" in s2 and "no phases" in s2
    # empty profiler
    assert "no rounds" in RoundProfiler(level=0).summary()


def test_labeled_counter_render_and_escaping():
    c = LabeledCounter("x_total", "phase", "help text")
    c.inc("grow", 1.5)
    c.inc('we"ird\nname', 1)
    text = c.render()
    assert '# HELP x_total help text' in text
    assert '# TYPE x_total counter' in text
    assert 'x_total{phase="grow"} 1.5' in text
    assert r'we\"ird\nname' in text


# ----------------------------------------------------- exposition lint
def _lint_exposition(text):
    """promtool-style lint: every sample belongs to a family that
    declared HELP and TYPE; histogram buckets are cumulative and end at
    +Inf == _count; no family is declared twice."""
    helps, types = {}, {}
    samples = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in helps, f"duplicate HELP for {name}"
            helps[name] = line
        elif line.startswith("# TYPE "):
            name = line.split()[2]
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = line.split()[3]
        else:
            m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)$",
                         line)
            assert m, f"unparseable sample line: {line!r}"
            samples.append((m.group(1), m.group(2), float(m.group(3))))
    for name, labels, _ in samples:
        family = re.sub(r"_(bucket|sum|count)$", "", name) \
            if re.sub(r"_(bucket|sum|count)$", "", name) in types else name
        assert family in types, f"sample {name} has no TYPE"
        assert family in helps, f"sample {name} has no HELP"
    # histogram bucket discipline
    hists = [n for n, t in types.items() if t == "histogram"]
    for h in hists:
        buckets = [(labels, v) for n, labels, v in samples
                   if n == f"{h}_bucket"]
        counts = [v for n, _, v in samples if n == f"{h}_count"]
        assert buckets and len(counts) == 1, h
        vals = [v for _, v in buckets]
        assert vals == sorted(vals), f"{h} buckets not cumulative"
        assert buckets[-1][0] == '{le="+Inf"}', h
        assert vals[-1] == counts[0], f"{h} +Inf bucket != _count"
    return types


def test_exposition_lint_full_registry():
    # make every group exist and carry data
    obs.training_metrics().phase_seconds.inc("grow", 0.5)
    obs.training_metrics().round_seconds.observe(0.01)
    obs.reliability_metrics()
    comm.record("allreduce", nbytes=10, seconds=0.1)
    types = _lint_exposition(obs.registry().render(exclude=("serving",)))
    for fam in ("xgbtpu_training_rounds_total",
                "xgbtpu_training_phase_seconds_total",
                "xgbtpu_training_round_seconds",
                "xgbtpu_comm_allreduce_total",
                "xgbtpu_comm_allreduce_bytes_total",
                "xgbtpu_comm_allreduce_seconds_total",
                "xgbtpu_reliability_integrity_failures_total"):
        assert fam in types, f"{fam} missing"


def test_exposition_lint_serving_metrics():
    from xgboost_tpu.profiling import ServingMetrics
    m = ServingMetrics()
    m.latency.observe(0.003)
    m.latency.observe(0.3)
    m.batch_rows.observe(4)
    types = _lint_exposition(m.render())
    assert types["xgbtpu_serving_latency_seconds"] == "histogram"
    assert "xgbtpu_reliability_integrity_failures_total" in types


# ------------------------------------------------------- spans + events
def test_span_nesting_and_trace_propagation(obs_log):
    with trace.trace_context("req-42"):
        with obs.span("outer", a=1) as sp:
            sp.set("b", 2)
            with obs.span("inner"):
                pass
    recs = _records(obs_log)
    inner = next(r for r in recs if r["name"] == "inner")
    outer = next(r for r in recs if r["name"] == "outer")
    assert outer["trace"] == inner["trace"] == "req-42"
    assert inner["parent"] == outer["span"]
    assert "parent" not in outer
    assert outer["attrs"] == {"a": 1, "b": 2}
    assert outer["dur_ms"] >= inner["dur_ms"] >= 0


def test_span_error_status(obs_log):
    with pytest.raises(ValueError):
        with obs.span("boom"):
            raise ValueError("nope")
    rec = _records(obs_log)[-1]
    assert rec["status"] == "error" and "nope" in rec["error"]


def test_span_is_noop_without_log():
    # no log configured: no ids generated, nesting depth still
    # consistent, nothing written
    assert obs.get_log() is None
    with obs.span("quiet") as sp:
        assert sp.span_id is None
        with obs.span("inner"):
            pass
        assert trace.current_span_id() is None  # sentinel, not an id
    assert not getattr(trace._tls, "spans", [])


def test_event_log_rotation(tmp_path):
    path = str(tmp_path / "rot.jsonl")
    log = obs.configure_log(path, rotate_bytes=512)
    try:
        for i in range(100):
            log.emit({"i": i, "pad": "x" * 32})
        assert os.path.exists(path + ".1"), "no rotation happened"
        # both generations parse line-by-line
        for p in (path, path + ".1"):
            assert all(json.loads(l) for l in open(p) if l.strip())
    finally:
        obs.configure_log(None)


def test_faults_emit_obs_events(obs_log, tmp_path):
    from xgboost_tpu.reliability import faults, integrity
    f = tmp_path / "victim.bin"
    f.write_bytes(b"payload")
    faults.inject("read_flip", 0, path_sub="victim")
    try:
        integrity.read_file(str(f))
    finally:
        faults.clear_faults()
    evs = [r for r in _records(obs_log) if r["kind"] == "event"
           and r["name"] == "fault.injected"]
    assert len(evs) == 1
    assert evs[0]["attrs"]["kind"] == "read_flip"
    assert evs[0]["attrs"]["seam"] == "read"
    assert "victim" in evs[0]["attrs"]["path"]


def test_integrity_failure_emits_event(obs_log):
    from xgboost_tpu.reliability.integrity import (ModelIntegrityError,
                                                   add_footer,
                                                   verify_model_bytes)
    raw = bytearray(add_footer(b"model-bytes"))
    raw[3] ^= 0x40
    with pytest.raises(ModelIntegrityError):
        verify_model_bytes(bytes(raw), name="flipped.bin")
    evs = [r for r in _records(obs_log)
           if r["name"] == "integrity.failure"]
    assert evs and evs[0]["attrs"]["file"] == "flipped.bin"


# ------------------------------------------------------------ training
def test_training_rounds_emit_timeline_and_metrics(obs_log):
    comm.reset_for_tests()
    from xgboost_tpu.parallel import mock
    rounds0 = obs.training_metrics().rounds.value
    ar0 = comm.metrics().count["allreduce"].value
    calls0 = mock.collective_calls()
    _train(rounds=3)
    assert obs.training_metrics().rounds.value - rounds0 == 3
    # comm allreduce count matches the mock seam's collective calls
    assert (comm.metrics().count["allreduce"].value - ar0
            == mock.collective_calls() - calls0 == 3)
    for r in range(3):
        rs = comm.round_stats(r)
        assert rs["allreduce"]["count"] == 1
        assert rs["allreduce"]["seconds"] > 0
    recs = _records(obs_log)
    rounds = [r for r in recs if r["name"] == "train.round"]
    phases = [r for r in recs if r["name"] == "train.phase"]
    assert [r["round"] for r in rounds] == [0, 1, 2]
    assert {p["attrs"]["phase"] for p in phases} >= {"predict", "grow"}
    assert all(r["attrs"]["phases_ms"] for r in rounds)
    assert all(r["attrs"]["comm"]["allreduce"]["count"] >= 1
               for r in rounds)


def test_eval_scores_exported_as_gauges():
    rng = np.random.RandomState(3)
    X = rng.rand(200, 4).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    xgb.train({"objective": "binary:logistic", "silent": 1}, d, 2,
              evals=[(d, "train")], verbose_eval=False)
    vals = obs.training_metrics().eval_score.values()
    assert any(k.startswith("train-") for k in vals)


def test_cli_train_scrape_and_timeline(tmp_path):
    """Acceptance: CLI train with metrics_port= is scrapeable over HTTP
    while running, and obs_log= leaves a timeline obs_report renders."""
    rng = np.random.RandomState(11)
    X = rng.rand(300, 5).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    train = tmp_path / "train.svm"
    with open(train, "w") as f:
        for row, label in zip(X, y):
            feats = " ".join(f"{j}:{v:.6f}" for j, v in enumerate(row))
            f.write(f"{label:g} {feats}\n")
    log = str(tmp_path / "run.jsonl")
    model = str(tmp_path / "m.model")
    from xgboost_tpu.cli import main as cli_main
    rc = {}

    def run():
        rc["rc"] = cli_main([
            f"data={train}", "task=train", "num_round=40",
            "objective=binary:logistic", "max_depth=3", "silent=1",
            f"eval[train]={train}", f"model_out={model}",
            f"obs_log={log}", "metrics_port=0"])

    t = threading.Thread(target=run)
    t.start()
    try:
        # the server comes up before the first round; scrape it LIVE
        srv = None
        for _ in range(2000):
            srv = obs.get_metrics_server()
            if srv is not None:
                break
            time.sleep(0.005)
        assert srv is not None, "metrics server never started"
        base = f"http://{srv.host}:{srv.port}"
        mid_run, text = False, ""
        while t.is_alive():
            r = urllib.request.urlopen(base + "/metrics", timeout=5)
            assert r.headers["Content-Type"] == \
                "text/plain; version=0.0.4; charset=utf-8"
            text = r.read().decode()
            m = re.search(r"^xgbtpu_training_rounds_total (\d+)", text,
                          re.M)
            if m and int(m.group(1)) > 0:
                mid_run = True
                break
            time.sleep(0.002)
        t.join(120)
        assert rc.get("rc") == 0
        if not mid_run:  # training beat the poll loop: scrape post-run
            text = urllib.request.urlopen(
                base + "/metrics", timeout=5).read().decode()
        for fam in ("xgbtpu_training_rounds_total",
                    "xgbtpu_training_phase_seconds_total",
                    "xgbtpu_training_eval_score",
                    "xgbtpu_comm_allreduce_total"):
            assert fam in text, f"{fam} missing from scrape"
        h = json.load(urllib.request.urlopen(base + "/healthz", timeout=5))
        assert h["status"] == "ok" and h["rounds_completed"] >= 40
        assert h["uptime_seconds"] >= 0
    finally:
        t.join(120)
        obs.stop_metrics_server()
        obs.configure_log(None)
    # the timeline renders per-round phase lines
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         log, "--rounds"], capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "== training: 40 rounds ==" in out.stdout
    assert "round   39" in out.stdout
    assert "grow=" in out.stdout


# ------------------------------------------------------------- serving
def test_request_id_echo_and_span(tmp_path, obs_log):
    from xgboost_tpu.serving import run_server
    bst, X, _ = _train(seed=5)
    path = str(tmp_path / "m.bin")
    bst.save_model(path)
    srv = run_server(path, port=0, min_bucket=8, max_bucket=32,
                     max_wait_ms=1, poll_sec=0, warmup=False,
                     quiet=True, block=False)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = ",".join("0.5" for _ in range(6)).encode()
        req = urllib.request.Request(base + "/predict", data=body,
                                     method="POST")
        req.add_header("X-Request-Id", "trace-me-123")
        resp = urllib.request.urlopen(req)
        assert resp.headers["X-Request-Id"] == "trace-me-123"
        json.load(resp)
        # a request WITHOUT the header still gets a generated id echoed
        resp2 = urllib.request.urlopen(urllib.request.Request(
            base + "/predict", data=body, method="POST"))
        assert resp2.headers["X-Request-Id"]
        # prometheus content type on serving /metrics too
        m = urllib.request.urlopen(base + "/metrics")
        assert m.headers["Content-Type"] == \
            "text/plain; version=0.0.4; charset=utf-8"
        h = json.load(urllib.request.urlopen(base + "/healthz"))
        assert h["uptime_seconds"] >= 0 and h["model_version"] == 1
    finally:
        srv.shutdown()
    recs = _records(obs_log)
    spans = [r for r in recs if r["name"] == "serve.request"
             and r["trace"] == "trace-me-123"]
    assert len(spans) == 1
    sp = spans[0]
    assert sp["attrs"]["request_id"] == "trace-me-123"
    assert sp["attrs"]["status"] == 200 and sp["attrs"]["rows"] == 1
    # the device batch span names the request it coalesced
    batches = [r for r in recs if r["name"] == "serve.batch"]
    assert any("trace-me-123" in b["attrs"].get("request_ids", [])
               for b in batches)


# ------------------------------------------------------------- tooling
def test_obs_report_selftest():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--selftest"], capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert "obs_report selftest: OK" in out.stdout


def test_profiling_compat_shim():
    from xgboost_tpu.profiling import (Counter, Gauge,  # noqa: F401
                                       Histogram, ReliabilityMetrics,
                                       RoundProfiler, ServingMetrics,
                                       reliability_metrics)
    from xgboost_tpu.obs.profiler import RoundProfiler as ObsRP
    assert RoundProfiler is ObsRP
    assert reliability_metrics() is obs.reliability_metrics()


# ------------------------------------------------------ multi-process
@pytest.mark.skipif(
    not hasattr(__import__("jax").sharding, "AxisType"),
    reason="jax too old for mesh axis types (all mesh paths unavailable)")
def test_mp_comm_stats_per_rank(tmp_path):
    """Acceptance: per-rank collective stats across REAL processes —
    nonzero allreduce count/bytes/seconds per round, the count matching
    the mock seam, and cross-worker aggregation via the mesh
    collective."""
    rng = np.random.RandomState(0)
    X = rng.rand(512, 6).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0.8).astype(np.float32)
    train = tmp_path / "train.svm"
    with open(train, "w") as f:
        for row, label in zip(X, y):
            feats = " ".join(f"{j}:{v:.6f}" for j, v in enumerate(row))
            f.write(f"{label:g} {feats}\n")
    prefix = str(tmp_path / "comm")
    n_rounds = 3
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-m", "xgboost_tpu.launch", "-n", "2",
           "--local-devices", "2", "--",
           sys.executable, os.path.join(REPO, "tests",
                                        "mp_comm_worker.py"),
           str(train), prefix, str(n_rounds)]
    r = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    reports = []
    for rank in (0, 1):
        with open(f"{prefix}.rank{rank}.json") as f:
            reports.append(json.load(f))
    for rep in reports:
        tot = rep["totals"]["allreduce"]
        # count matches the number of collective calls the mock seam
        # recorded in that process
        assert tot["count"] == rep["mock_calls"] == n_rounds
        assert tot["bytes"] > 0 and tot["seconds"] > 0
        for rnd in range(n_rounds):
            per = rep["per_round"][str(rnd)]["allreduce"]
            assert per["count"] == 1
            assert per["bytes"] > 0 and per["seconds"] > 0
        # per-rank export: the rendered registry carries the families
        for fam in ("xgbtpu_comm_allreduce_total",
                    "xgbtpu_comm_allreduce_bytes_total",
                    "xgbtpu_comm_allreduce_seconds_total"):
            assert fam in rep["metrics_text"]
    # aggregation across workers used the mesh collective and sums the
    # per-rank totals
    agg = reports[0]["aggregated"]["allreduce"]
    assert agg["count"] == sum(
        rep["totals"]["allreduce"]["count"] for rep in reports)

"""Cluster submitters (parallel/submit.py) — the rabit_mpi/sge/yarn
submitter analog.  No scheduler exists in CI, so the tests assert the
constructed commands/scripts (dry-run) and the env contract round-trip
(scheduler vars -> rank/world)."""

import os
import subprocess
import sys

import pytest

from xgboost_tpu.parallel.launch import COORD_ENV, NWORKER_ENV, RANK_ENV
from xgboost_tpu.parallel.submit import (mpi_command, scheduler_rank,
                                         sge_script, slurm_command, submit)


def test_mpi_command_exports_contract():
    line = mpi_command(4, "h0:9", ["python", "w.py"])
    assert line[:3] == ["mpirun", "-n", "4"]
    assert f"{COORD_ENV}=h0:9" in line and f"{NWORKER_ENV}=4" in line
    assert line[-2:] == ["python", "w.py"]


def test_sge_script_maps_task_id_to_rank():
    s = sge_script(3, "h0:9", ["python", "w.py", "a b"])
    assert "#$ -t 1-3" in s
    assert f"export {COORD_ENV}=h0:9" in s
    assert f"export {RANK_ENV}=$((SGE_TASK_ID-1))" in s
    assert "exec python w.py 'a b'" in s


def test_slurm_command():
    line = slurm_command(2, "h0:9", ["w"])
    assert line[0] == "srun" and "--ntasks=2" in line


def test_scheduler_rank_resolution(monkeypatch):
    for v in ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE",
              "SLURM_PROCID", "SLURM_NTASKS", "SGE_TASK_ID",
              "SGE_TASK_LAST", "PMI_RANK", "PMI_SIZE"):
        monkeypatch.delenv(v, raising=False)
    assert scheduler_rank() is None
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "2")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "8")
    assert scheduler_rank() == (2, 8)
    monkeypatch.delenv("OMPI_COMM_WORLD_RANK")
    monkeypatch.delenv("OMPI_COMM_WORLD_SIZE")
    monkeypatch.setenv("SGE_TASK_ID", "1")
    monkeypatch.setenv("SGE_TASK_LAST", "4")
    assert scheduler_rank() == (0, 4)


def test_submit_dry_run_cli():
    r = subprocess.run(
        [sys.executable, "-m", "xgboost_tpu.parallel.submit", "-n", "2",
         "--mode", "sge", "--coord", "h:1", "--dry-run", "--",
         "python", "w.py"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr
    assert "#$ -t 1-2" in r.stdout


def test_submit_needs_coord_for_schedulers():
    with pytest.raises(ValueError, match="--coord"):
        submit(2, ["w"], mode="sge", dry_run=True)

/*
 * C ABI for xgboost_tpu — serves non-Python hosts (R, C/C++, JVM via
 * JNI, ...).  The function surface mirrors the reference's C wrapper
 * (reference wrapper/xgboost_wrapper.h:26-235) so existing bindings
 * port by relinking; the implementation embeds the Python runtime and
 * drives the JAX/TPU core through xgboost_tpu.capi_bridge.
 *
 * Memory contract (same as the reference): pointers returned by
 * *GetFloatInfo / *GetUIntInfo / Predict / EvalOneIter / GetModelRaw /
 * DumpModel stay valid until the next call of the same function on the
 * same handle, or until the handle is freed.
 *
 * Errors print a Python traceback to stderr and abort the process
 * (the reference's utils::Error behavior).
 */
#ifndef XGBOOST_TPU_CAPI_H_
#define XGBOOST_TPU_CAPI_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned long xgt_ulong;

/* ---- DMatrix ---- */
void *XGDMatrixCreateFromFile(const char *fname, int silent);
void *XGDMatrixCreateFromCSR(const xgt_ulong *indptr, const unsigned *indices,
                             const float *data, xgt_ulong nindptr,
                             xgt_ulong nelem);
void *XGDMatrixCreateFromCSC(const xgt_ulong *col_ptr, const unsigned *indices,
                             const float *data, xgt_ulong nindptr,
                             xgt_ulong nelem);
void *XGDMatrixCreateFromMat(const float *data, xgt_ulong nrow,
                             xgt_ulong ncol, float missing);
void *XGDMatrixSliceDMatrix(void *handle, const int *idxset, xgt_ulong len);
void XGDMatrixFree(void *handle);
void XGDMatrixSaveBinary(void *handle, const char *fname, int silent);
void XGDMatrixSetFloatInfo(void *handle, const char *field,
                           const float *array, xgt_ulong len);
void XGDMatrixSetUIntInfo(void *handle, const char *field,
                          const unsigned *array, xgt_ulong len);
void XGDMatrixSetGroup(void *handle, const unsigned *group, xgt_ulong len);
const float *XGDMatrixGetFloatInfo(const void *handle, const char *field,
                                   xgt_ulong *out_len);
const unsigned *XGDMatrixGetUIntInfo(const void *handle, const char *field,
                                     xgt_ulong *out_len);
xgt_ulong XGDMatrixNumRow(const void *handle);

/* ---- Booster ---- */
void *XGBoosterCreate(void *dmats[], xgt_ulong len);
void XGBoosterFree(void *handle);
void XGBoosterSetParam(void *handle, const char *name, const char *value);
void XGBoosterUpdateOneIter(void *handle, int iter, void *dtrain);
void XGBoosterBoostOneIter(void *handle, void *dtrain, float *grad,
                           float *hess, xgt_ulong len);
const char *XGBoosterEvalOneIter(void *handle, int iter, void *dmats[],
                                 const char *evnames[], xgt_ulong len);
const float *XGBoosterPredict(void *handle, void *dmat, int option_mask,
                              unsigned ntree_limit, xgt_ulong *out_len);
void XGBoosterLoadModel(void *handle, const char *fname);
void XGBoosterSaveModel(const void *handle, const char *fname);
void XGBoosterLoadModelFromBuffer(void *handle, const void *buf,
                                  xgt_ulong len);
const char *XGBoosterGetModelRaw(void *handle, xgt_ulong *out_len);
const char **XGBoosterDumpModel(void *handle, const char *fmap,
                                int with_stats, xgt_ulong *out_len);

#ifdef __cplusplus
}
#endif
#endif /* XGBOOST_TPU_CAPI_H_ */

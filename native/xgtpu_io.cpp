// xgtpu_io: native IO runtime for xgboost_tpu.
//
// TPU-native counterpart of the reference's host-side IO machinery:
//   - multithreaded libsvm text parsing (reference src/io/libsvm_parser.h:
//     chunked parsing split at line boundaries, there with OpenMP; here
//     std::thread over byte ranges with a line-count prefix pass so
//     rank/npart row sharding is deterministic).
//   - external-memory sparse page store with a background prefetch
//     thread (reference src/io/sparse_batch_page.h page format +
//     src/utils/thread_buffer.h double-buffer producer).
//
// Exposed as a C ABI consumed via ctypes (xgboost_tpu/native.py).
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread (see Makefile).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kPageMagic = 0xFFAB7C02D1E5F00DULL;

struct CSRChunk {
  std::vector<int64_t> row_ptr;   // local, starts at 0
  std::vector<int32_t> indices;
  std::vector<float> values;
  std::vector<float> labels;
  bool error = false;             // malformed input seen
};

struct CSRResult {
  std::vector<int64_t> indptr;
  std::vector<int32_t> indices;
  std::vector<float> values;
  std::vector<float> labels;
};

// ------------------------------------------------------------------ parse
inline const char* parse_float(const char* p, const char* /*end*/,
                               float* out) {
  // Returns the RAW stop position (may exceed the line end when strtof
  // skips the newline into the next line — callers treat that as a
  // malformed-input error rather than clamping it away).
  char* q;
  *out = strtof(p, &q);
  return q;
}

void parse_range(const char* data, size_t begin, size_t end_,
                 int64_t first_line, int rank, int nparts, CSRChunk* out) {
  const char* p = data + begin;
  const char* end = data + end_;
  int64_t line = first_line;
  while (p < end) {
    const char* eol = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (eol == nullptr) eol = end;
    bool keep = (nparts <= 1) || (line % nparts == rank);
    if (keep) {
      // skip blank lines
      const char* q = p;
      while (q < eol && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
      if (q < eol) {
        float label;
        const char* after = parse_float(q, eol, &label);
        if (after == q || after > eol) {  // unparseable label
          out->error = true;
          return;
        }
        q = after;
        out->labels.push_back(label);
        while (q < eol) {
          while (q < eol && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
          if (q >= eol) break;
          // malformed tokens are hard errors, matching the Python
          // fallback which raises ValueError on int()/float() failure
          char* colon;
          long idx = strtol(q, &colon, 10);
          if (colon == q || colon >= eol || *colon != ':' ||
              colon + 1 >= eol) {
            out->error = true;
            return;
          }
          float v;
          after = parse_float(colon + 1, eol, &v);
          if (after == colon + 1 || after > eol) {  // empty/cross-line value
            out->error = true;
            return;
          }
          q = after;
          out->indices.push_back(static_cast<int32_t>(idx));
          out->values.push_back(v);
        }
        out->row_ptr.push_back(static_cast<int64_t>(out->indices.size()));
      }
    }
    ++line;
    p = eol + 1;
  }
}

// status: 0 ok, 1 cannot open/read, 2 malformed input
CSRResult* parse_libsvm(const char* path, int nthread, int rank, int nparts,
                        int* status) {
  std::vector<CSRChunk> chunks;
  {
    // scope the file buffer: freed before the merge so peak memory is
    // chunks + result, not buffer + chunks + result
    std::ifstream f(path, std::ios::binary | std::ios::ate);
    if (!f) {
      *status = 1;
      return nullptr;
    }
    size_t size = static_cast<size_t>(f.tellg());
    f.seekg(0);
    std::string buf(size, '\0');
    if (size && !f.read(&buf[0], static_cast<std::streamsize>(size))) {
      *status = 1;
      return nullptr;
    }

    if (nthread <= 0)
      nthread = static_cast<int>(std::thread::hardware_concurrency());
    if (nthread < 1) nthread = 1;
    if (size < (1u << 16)) nthread = 1;  // small file: no parallel win

    // chunk boundaries aligned to line starts
    std::vector<size_t> bounds{0};
    for (int t = 1; t < nthread; ++t) {
      size_t target = size * static_cast<size_t>(t) / nthread;
      const void* nl = memchr(buf.data() + target, '\n', size - target);
      size_t b = nl ? static_cast<size_t>(static_cast<const char*>(nl) -
                                          buf.data()) + 1
                    : size;
      if (b <= bounds.back()) b = bounds.back();
      bounds.push_back(b);
    }
    bounds.push_back(size);

    // prefix pass: global line index at each chunk start, so rank/npart
    // sharding picks exactly the rows `line % nparts == rank` regardless
    // of thread count (deterministic split loading,
    // reference simple_dmatrix-inl.hpp:89-96)
    std::vector<int64_t> first_line(bounds.size() - 1, 0);
    {
      int64_t acc = 0;
      for (size_t c = 0; c + 1 < bounds.size(); ++c) {
        first_line[c] = acc;
        const char* p = buf.data() + bounds[c];
        const char* end = buf.data() + bounds[c + 1];
        while (p < end) {
          const void* nl = memchr(p, '\n', static_cast<size_t>(end - p));
          if (!nl) { ++acc; break; }
          ++acc;
          p = static_cast<const char*>(nl) + 1;
        }
      }
    }

    chunks.resize(static_cast<size_t>(nthread));
    std::vector<std::thread> workers;
    for (int t = 0; t < nthread; ++t) {
      workers.emplace_back(parse_range, buf.data(), bounds[t], bounds[t + 1],
                           first_line[t], rank, nparts, &chunks[t]);
    }
    for (auto& w : workers) w.join();
  }

  for (const auto& c : chunks) {
    if (c.error) {
      *status = 2;
      return nullptr;
    }
  }

  auto* res = new CSRResult();
  size_t n_rows = 0, nnz = 0;
  for (const auto& c : chunks) {
    n_rows += c.labels.size();
    nnz += c.indices.size();
  }
  res->indptr.reserve(n_rows + 1);
  res->indptr.push_back(0);
  res->indices.reserve(nnz);
  res->values.reserve(nnz);
  res->labels.reserve(n_rows);
  for (auto& c : chunks) {
    int64_t base = res->indptr.back();
    for (int64_t rp : c.row_ptr) res->indptr.push_back(base + rp);
    res->indices.insert(res->indices.end(), c.indices.begin(),
                        c.indices.end());
    res->values.insert(res->values.end(), c.values.begin(), c.values.end());
    res->labels.insert(res->labels.end(), c.labels.begin(), c.labels.end());
    c = CSRChunk();  // release each chunk as it is merged
  }
  *status = 0;
  return res;
}

// ------------------------------------------------------------- page store
struct Page {
  std::vector<int64_t> indptr;  // n_rows + 1
  std::vector<int32_t> indices;
  std::vector<float> values;
  int64_t n_rows() const { return static_cast<int64_t>(indptr.size()) - 1; }
};

struct PageWriter {
  std::ofstream out;
  explicit PageWriter(const char* path) : out(path, std::ios::binary) {
    out.write(reinterpret_cast<const char*>(&kPageMagic), sizeof(kPageMagic));
  }
  bool push(int64_t n_rows, const int64_t* indptr, const int32_t* indices,
            const float* values) {
    int64_t n_entries = indptr[n_rows] - indptr[0];
    out.write(reinterpret_cast<const char*>(&n_rows), 8);
    out.write(reinterpret_cast<const char*>(&n_entries), 8);
    // rebased indptr
    std::vector<int64_t> rebased(static_cast<size_t>(n_rows) + 1);
    for (int64_t i = 0; i <= n_rows; ++i) rebased[static_cast<size_t>(i)] =
        indptr[i] - indptr[0];
    out.write(reinterpret_cast<const char*>(rebased.data()),
              static_cast<std::streamsize>(8 * (n_rows + 1)));
    out.write(reinterpret_cast<const char*>(indices + indptr[0]),
              static_cast<std::streamsize>(4 * n_entries));
    out.write(reinterpret_cast<const char*>(values + indptr[0]),
              static_cast<std::streamsize>(4 * n_entries));
    return static_cast<bool>(out);
  }
};

// Background prefetch reader: producer thread keeps one page ahead
// (the reference's ThreadBuffer<Page> double buffer, thread_buffer.h).
struct PageReader {
  std::ifstream in;
  std::thread producer;
  std::mutex mu;
  std::condition_variable cv;
  std::unique_ptr<Page> ready;      // produced, not yet consumed
  std::unique_ptr<Page> current;    // handed to consumer
  bool eof = false;
  bool stop = false;
  bool do_reset = false;
  bool ok = false;

  explicit PageReader(const char* path) : in(path, std::ios::binary) {
    uint64_t magic = 0;
    in.read(reinterpret_cast<char*>(&magic), sizeof(magic));
    if (!in || magic != kPageMagic) {
      eof = true;
      return;
    }
    ok = true;
    producer = std::thread([this] { this->run(); });
  }

  ~PageReader() {
    {
      std::lock_guard<std::mutex> g(mu);
      stop = true;
    }
    cv.notify_all();
    if (producer.joinable()) producer.join();
  }

  std::unique_ptr<Page> read_one() {
    auto p = std::make_unique<Page>();
    int64_t n_rows = 0, n_entries = 0;
    if (!in.read(reinterpret_cast<char*>(&n_rows), 8)) return nullptr;
    if (!in.read(reinterpret_cast<char*>(&n_entries), 8)) return nullptr;
    p->indptr.resize(static_cast<size_t>(n_rows) + 1);
    p->indices.resize(static_cast<size_t>(n_entries));
    p->values.resize(static_cast<size_t>(n_entries));
    if (!in.read(reinterpret_cast<char*>(p->indptr.data()),
                 static_cast<std::streamsize>(8 * (n_rows + 1))))
      return nullptr;
    if (n_entries) {
      if (!in.read(reinterpret_cast<char*>(p->indices.data()),
                   static_cast<std::streamsize>(4 * n_entries)))
        return nullptr;
      if (!in.read(reinterpret_cast<char*>(p->values.data()),
                   static_cast<std::streamsize>(4 * n_entries)))
        return nullptr;
    }
    return p;
  }

  void run() {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      if (stop) return;
      if (do_reset) {
        in.clear();
        in.seekg(static_cast<std::streamoff>(sizeof(kPageMagic)));
        eof = false;
        ready.reset();
        do_reset = false;
        cv.notify_all();
        continue;
      }
      if (!eof && ready == nullptr) {
        lk.unlock();                       // read without holding the lock
        std::unique_ptr<Page> p = read_one();
        lk.lock();
        if (stop || do_reset) continue;    // discard the stale read
        if (!p)
          eof = true;
        else
          ready = std::move(p);
        cv.notify_all();
        continue;
      }
      cv.wait(lk, [this] {
        return stop || do_reset || (!eof && ready == nullptr);
      });
    }
  }

  // returns the next page or nullptr at EOF
  bool next() {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [this] { return ready != nullptr || eof; });
    if (!ready) {
      current.reset();
      return false;
    }
    current = std::move(ready);
    cv.notify_all();  // wake producer to prefetch the next page
    return true;
  }

  void reset() {
    std::unique_lock<std::mutex> lk(mu);
    do_reset = true;
    current.reset();
    cv.notify_all();
    cv.wait(lk, [this] { return !do_reset || stop; });
  }
};

}  // namespace

// ------------------------------------------------------------------ C ABI
extern "C" {

// status out-param: 0 ok, 1 cannot open/read, 2 malformed input
void* XGTParseLibSVM(const char* path, int nthread, int rank, int nparts,
                     int* status) {
  return parse_libsvm(path, nthread, rank, nparts, status);
}

void XGTCSRSizes(void* handle, int64_t* n_rows, int64_t* n_entries) {
  auto* r = static_cast<CSRResult*>(handle);
  *n_rows = static_cast<int64_t>(r->labels.size());
  *n_entries = static_cast<int64_t>(r->indices.size());
}

void XGTCSRCopy(void* handle, int64_t* indptr, int32_t* indices,
                float* values, float* labels) {
  auto* r = static_cast<CSRResult*>(handle);
  memcpy(indptr, r->indptr.data(), 8 * r->indptr.size());
  if (!r->indices.empty()) {
    memcpy(indices, r->indices.data(), 4 * r->indices.size());
    memcpy(values, r->values.data(), 4 * r->values.size());
  }
  if (!r->labels.empty())
    memcpy(labels, r->labels.data(), 4 * r->labels.size());
}

void XGTCSRFree(void* handle) { delete static_cast<CSRResult*>(handle); }

void* XGTPageWriterCreate(const char* path) {
  auto* w = new PageWriter(path);
  if (!w->out) {
    delete w;
    return nullptr;
  }
  return w;
}

int XGTPageWriterPush(void* handle, int64_t n_rows, const int64_t* indptr,
                      const int32_t* indices, const float* values) {
  return static_cast<PageWriter*>(handle)->push(n_rows, indptr, indices,
                                                values)
             ? 0
             : -1;
}

void XGTPageWriterClose(void* handle) {
  delete static_cast<PageWriter*>(handle);
}

void* XGTPageReaderCreate(const char* path) {
  auto* r = new PageReader(path);
  if (!r->ok) {
    delete r;
    return nullptr;
  }
  return r;
}

// 1 = have page, 0 = EOF
int XGTPageReaderNext(void* handle, int64_t* n_rows, int64_t* n_entries) {
  auto* r = static_cast<PageReader*>(handle);
  if (!r->next()) {
    *n_rows = 0;
    *n_entries = 0;
    return 0;
  }
  *n_rows = r->current->n_rows();
  *n_entries = static_cast<int64_t>(r->current->indices.size());
  return 1;
}

void XGTPageReaderCopy(void* handle, int64_t* indptr, int32_t* indices,
                       float* values) {
  auto* r = static_cast<PageReader*>(handle);
  Page* p = r->current.get();
  memcpy(indptr, p->indptr.data(), 8 * p->indptr.size());
  if (!p->indices.empty()) {
    memcpy(indices, p->indices.data(), 4 * p->indices.size());
    memcpy(values, p->values.data(), 4 * p->values.size());
  }
}

void XGTPageReaderReset(void* handle) {
  static_cast<PageReader*>(handle)->reset();
}

void XGTPageReaderFree(void* handle) {
  delete static_cast<PageReader*>(handle);
}

}  // extern "C"

/*
 * C ABI implementation: embeds CPython and drives
 * xgboost_tpu.capi_bridge (see xgboost_tpu_capi.h for the contract;
 * reference surface: wrapper/xgboost_wrapper.cpp:113-353).
 *
 * Handles are the bridge's integer registry keys boxed as void* — no
 * Python object pointers ever cross the ABI.
 *
 * Threading: every entry point brackets ALL Python work (call + result
 * conversion + decref) in PyGILState_Ensure/Release.  When this library
 * itself initializes the interpreter it releases the GIL afterwards
 * (PyEval_SaveThread), so calls may come from any host thread.
 * Returned buffers are owned by per-handle anchors inside the bridge
 * (freed with the handle / replaced by the next same-kind call).
 */
#include "xgboost_tpu_capi.h"

#include <Python.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static PyObject *g_bridge = NULL;
static pthread_once_t g_once = PTHREAD_ONCE_INIT;

static void die_on_pyerr(const char *where) {
  if (PyErr_Occurred()) {
    fprintf(stderr, "xgboost_tpu C API error in %s:\n", where);
    PyErr_Print();
    exit(-1);
  }
}

static void init_once(void) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_bridge = PyImport_ImportModule("xgboost_tpu.capi_bridge");
    die_on_pyerr("import xgboost_tpu.capi_bridge (is the package on "
                 "PYTHONPATH?)");
    /* release the GIL we hold after Py_InitializeEx so other host
     * threads can PyGILState_Ensure */
    PyEval_SaveThread();
  } else {
    PyGILState_STATE g = PyGILState_Ensure();
    g_bridge = PyImport_ImportModule("xgboost_tpu.capi_bridge");
    die_on_pyerr("import xgboost_tpu.capi_bridge");
    PyGILState_Release(g);
  }
}

static PyGILState_STATE capi_enter(void) {
  pthread_once(&g_once, init_once);
  return PyGILState_Ensure();
}

static void capi_exit(PyGILState_STATE g) { PyGILState_Release(g); }

/* Call a bridge function (GIL must be held). Returns a new reference
 * (never NULL — errors abort). */
static PyObject *bridge_call(const char *name, const char *fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  PyObject *args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  die_on_pyerr(name);
  PyObject *fn = PyObject_GetAttrString(g_bridge, name);
  die_on_pyerr(name);
  PyObject *res = PyObject_CallObject(fn, args);
  Py_XDECREF(fn);
  Py_XDECREF(args);
  die_on_pyerr(name);
  return res;
}

static long h_of(const void *handle) { return (long)(intptr_t)handle; }

/* ---- conversion helpers; GIL held, res consumed ---- */

static void *take_handle(PyObject *res) {
  long h = PyLong_AsLong(res);
  Py_DECREF(res);
  die_on_pyerr("handle");
  return (void *)(intptr_t)h;
}

static void take_void(PyObject *res) { Py_XDECREF(res); }

/* (addr, len) tuple -> pointer + out_len; buffer anchored bridge-side */
static void *take_addr_len(PyObject *res, xgt_ulong *out_len) {
  unsigned long long addr =
      PyLong_AsUnsignedLongLong(PyTuple_GetItem(res, 0));
  if (out_len != NULL)
    *out_len = (xgt_ulong)PyLong_AsUnsignedLongLong(PyTuple_GetItem(res, 1));
  Py_DECREF(res);
  die_on_pyerr("addr_len");
  return (void *)(uintptr_t)addr;
}

/* ------------------------------------------------------------- DMatrix */

void *XGDMatrixCreateFromFile(const char *fname, int silent) {
  PyGILState_STATE g = capi_enter();
  void *h = take_handle(bridge_call("dmatrix_from_file", "(si)", fname,
                                    silent));
  capi_exit(g);
  return h;
}

void *XGDMatrixCreateFromCSR(const xgt_ulong *indptr, const unsigned *indices,
                             const float *data, xgt_ulong nindptr,
                             xgt_ulong nelem) {
  PyGILState_STATE g = capi_enter();
  void *h = take_handle(bridge_call(
      "dmatrix_from_csr", "(KKKKK)", (unsigned long long)(uintptr_t)indptr,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, (unsigned long long)nindptr,
      (unsigned long long)nelem));
  capi_exit(g);
  return h;
}

void *XGDMatrixCreateFromCSC(const xgt_ulong *col_ptr, const unsigned *indices,
                             const float *data, xgt_ulong nindptr,
                             xgt_ulong nelem) {
  PyGILState_STATE g = capi_enter();
  void *h = take_handle(bridge_call(
      "dmatrix_from_csc", "(KKKKK)", (unsigned long long)(uintptr_t)col_ptr,
      (unsigned long long)(uintptr_t)indices,
      (unsigned long long)(uintptr_t)data, (unsigned long long)nindptr,
      (unsigned long long)nelem));
  capi_exit(g);
  return h;
}

void *XGDMatrixCreateFromMat(const float *data, xgt_ulong nrow,
                             xgt_ulong ncol, float missing) {
  PyGILState_STATE g = capi_enter();
  void *h = take_handle(bridge_call(
      "dmatrix_from_mat", "(KKKf)", (unsigned long long)(uintptr_t)data,
      (unsigned long long)nrow, (unsigned long long)ncol, missing));
  capi_exit(g);
  return h;
}

void *XGDMatrixSliceDMatrix(void *handle, const int *idxset, xgt_ulong len) {
  PyGILState_STATE g = capi_enter();
  void *h = take_handle(bridge_call(
      "dmatrix_slice", "(lKK)", h_of(handle),
      (unsigned long long)(uintptr_t)idxset, (unsigned long long)len));
  capi_exit(g);
  return h;
}

void XGDMatrixFree(void *handle) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("dmatrix_free", "(l)", h_of(handle)));
  capi_exit(g);
}

void XGDMatrixSaveBinary(void *handle, const char *fname, int silent) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("dmatrix_save_binary", "(lsi)", h_of(handle), fname,
                        silent));
  capi_exit(g);
}

void XGDMatrixSetFloatInfo(void *handle, const char *field,
                           const float *array, xgt_ulong len) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("dmatrix_set_float_info", "(lsKK)", h_of(handle),
                        field, (unsigned long long)(uintptr_t)array,
                        (unsigned long long)len));
  capi_exit(g);
}

void XGDMatrixSetUIntInfo(void *handle, const char *field,
                          const unsigned *array, xgt_ulong len) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("dmatrix_set_uint_info", "(lsKK)", h_of(handle),
                        field, (unsigned long long)(uintptr_t)array,
                        (unsigned long long)len));
  capi_exit(g);
}

void XGDMatrixSetGroup(void *handle, const unsigned *group, xgt_ulong len) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("dmatrix_set_group", "(lKK)", h_of(handle),
                        (unsigned long long)(uintptr_t)group,
                        (unsigned long long)len));
  capi_exit(g);
}

const float *XGDMatrixGetFloatInfo(const void *handle, const char *field,
                                   xgt_ulong *out_len) {
  PyGILState_STATE g = capi_enter();
  const float *p = (const float *)take_addr_len(
      bridge_call("dmatrix_get_float_info", "(ls)", h_of(handle), field),
      out_len);
  capi_exit(g);
  return p;
}

const unsigned *XGDMatrixGetUIntInfo(const void *handle, const char *field,
                                     xgt_ulong *out_len) {
  PyGILState_STATE g = capi_enter();
  const unsigned *p = (const unsigned *)take_addr_len(
      bridge_call("dmatrix_get_uint_info", "(ls)", h_of(handle), field),
      out_len);
  capi_exit(g);
  return p;
}

xgt_ulong XGDMatrixNumRow(const void *handle) {
  PyGILState_STATE g = capi_enter();
  PyObject *res = bridge_call("dmatrix_num_row", "(l)", h_of(handle));
  xgt_ulong n = (xgt_ulong)PyLong_AsUnsignedLongLong(res);
  Py_DECREF(res);
  capi_exit(g);
  return n;
}

/* ------------------------------------------------------------- Booster */

static PyObject *handle_list(void *handles[], xgt_ulong len) {
  PyObject *lst = PyList_New((Py_ssize_t)len);
  for (xgt_ulong i = 0; i < len; ++i)
    PyList_SetItem(lst, (Py_ssize_t)i, PyLong_FromLong(h_of(handles[i])));
  return lst;
}

void *XGBoosterCreate(void *dmats[], xgt_ulong len) {
  PyGILState_STATE g = capi_enter();
  void *h = take_handle(
      bridge_call("booster_create", "(N)", handle_list(dmats, len)));
  capi_exit(g);
  return h;
}

void XGBoosterFree(void *handle) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("booster_free", "(l)", h_of(handle)));
  capi_exit(g);
}

void XGBoosterSetParam(void *handle, const char *name, const char *value) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("booster_set_param", "(lss)", h_of(handle), name,
                        value));
  capi_exit(g);
}

void XGBoosterUpdateOneIter(void *handle, int iter, void *dtrain) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("booster_update_one_iter", "(lil)", h_of(handle),
                        iter, h_of(dtrain)));
  capi_exit(g);
}

void XGBoosterBoostOneIter(void *handle, void *dtrain, float *grad,
                           float *hess, xgt_ulong len) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("booster_boost_one_iter", "(llKKK)", h_of(handle),
                        h_of(dtrain), (unsigned long long)(uintptr_t)grad,
                        (unsigned long long)(uintptr_t)hess,
                        (unsigned long long)len));
  capi_exit(g);
}

const char *XGBoosterEvalOneIter(void *handle, int iter, void *dmats[],
                                 const char *evnames[], xgt_ulong len) {
  PyGILState_STATE g = capi_enter();
  PyObject *hs = handle_list(dmats, len);
  PyObject *names = PyList_New((Py_ssize_t)len);
  for (xgt_ulong i = 0; i < len; ++i)
    PyList_SetItem(names, (Py_ssize_t)i, PyUnicode_FromString(evnames[i]));
  const char *s = (const char *)take_addr_len(
      bridge_call("booster_eval_one_iter", "(liNN)", h_of(handle), iter, hs,
                  names),
      NULL);
  capi_exit(g);
  return s;
}

const float *XGBoosterPredict(void *handle, void *dmat, int option_mask,
                              unsigned ntree_limit, xgt_ulong *out_len) {
  PyGILState_STATE g = capi_enter();
  const float *p = (const float *)take_addr_len(
      bridge_call("booster_predict", "(llii)", h_of(handle), h_of(dmat),
                  option_mask, (int)ntree_limit),
      out_len);
  capi_exit(g);
  return p;
}

void XGBoosterLoadModel(void *handle, const char *fname) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("booster_load_model", "(ls)", h_of(handle), fname));
  capi_exit(g);
}

void XGBoosterSaveModel(const void *handle, const char *fname) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("booster_save_model", "(ls)", h_of(handle), fname));
  capi_exit(g);
}

void XGBoosterLoadModelFromBuffer(void *handle, const void *buf,
                                  xgt_ulong len) {
  PyGILState_STATE g = capi_enter();
  take_void(bridge_call("booster_load_model_from_buffer", "(lKK)",
                        h_of(handle), (unsigned long long)(uintptr_t)buf,
                        (unsigned long long)len));
  capi_exit(g);
}

const char *XGBoosterGetModelRaw(void *handle, xgt_ulong *out_len) {
  PyGILState_STATE g = capi_enter();
  const char *p = (const char *)take_addr_len(
      bridge_call("booster_get_model_raw", "(l)", h_of(handle)), out_len);
  capi_exit(g);
  return p;
}

const char **XGBoosterDumpModel(void *handle, const char *fmap,
                                int with_stats, xgt_ulong *out_len) {
  PyGILState_STATE g = capi_enter();
  const char **p = (const char **)take_addr_len(
      bridge_call("booster_dump_model", "(lsi)", h_of(handle),
                  fmap ? fmap : "", with_stats),
      out_len);
  capi_exit(g);
  return p;
}
